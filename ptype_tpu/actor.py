"""Actor server: register handlers, serve calls.

The reference's servers were stdlib ``net/rpc``: ``rpc.Register(&Calculator{})``
+ ``rpc.HandleHTTP()`` + ``http.ListenAndServe`` (example/calculator/server.go:
16-20,38). Here the equivalent is :class:`ActorServer`: register an object
(its public methods become ``Type.Method`` endpoints, net/rpc naming) or a
bare function, then ``serve()``.

TPU-native behaviors:
- payloads ride :mod:`ptype_tpu.codec`, so tensor args arrive as device
  buffers (``jax.device_put``) rather than pickled host objects;
- same-process calls short-circuit the socket entirely (see
  ``lookup_local``), which is how actor calls between services that share a
  host process stay zero-copy.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import traceback

from ptype_tpu import codec, logs, trace
from ptype_tpu.coord import wire
from ptype_tpu.errors import ShedError

log = logs.get_logger("actor")

# Process-local server registry for zero-copy same-process dispatch.
_local_servers: dict[tuple[str, int], "ActorServer"] = {}
_local_lock = threading.Lock()


def _profile_endpoint(cmd: str, options=None):
    """The built-in ``ptype.Profile`` handler — a lazy shim so actor.py
    stays import-light (profiling pulls in the health plane; this
    module must import before it)."""
    from ptype_tpu.health import profiling

    return profiling.endpoint(cmd, options)


def lookup_local(address: str, port: int) -> "ActorServer | None":
    with _local_lock:
        server = _local_servers.get((address, port))
    if server is not None and not server.serving:
        return None
    return server


class ActorServer:
    """Registers handlers and serves actor calls over TCP."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        # Default binds all interfaces, matching the reference's
        # http.ListenAndServe(":port") (server.go:38) — the registry
        # advertises the host's routable IP (cluster.go:198-213), so the
        # server must be reachable on it.
        self._handlers: dict[str, object] = {}
        # Built-in observability endpoints: every actor server answers
        # the cluster telemetry pull plane (metrics snapshot + recent
        # spans from the flight recorder) and the profiling plane
        # (jax.profiler XPlane capture + HBM snapshots) without
        # registration — ptype_tpu.telemetry.cluster_snapshot /
        # cluster_profile walk the registry and call these per node.
        self._handlers["ptype.Telemetry"] = trace.telemetry
        self._handlers["ptype.Profile"] = _profile_endpoint
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(128)
        self.host, self.port = self._sock.getsockname()[:2]
        self._closed = threading.Event()
        self._thread: threading.Thread | None = None
        #: Live accepted connections, so close() can shut them down —
        #: a reader parked in recv(2) is not woken by close() alone and
        #: would otherwise outlive the server as a wedged thread.
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()

    # ------------------------------------------------------------ handlers

    def register(self, obj: object, name: str = "") -> None:
        """Expose ``obj``'s EXPORTED methods — leading-uppercase names,
        Go's net/rpc rule (ref example/calculator/calculator.go:9-12
        exposes ``Calculator.Multiply``) — as ``Name.Method`` endpoints.
        Lowercase methods (``close``, ``params``…) are the actor's
        local/lifecycle surface and must not be remotely callable: a
        reflected ``Generator.close`` would let any client shut the
        server's generation down. ``register_function`` remains the
        explicit escape hatch for any name."""
        name = name or type(obj).__name__
        for attr in dir(obj):
            if not attr[:1].isupper():
                continue
            fn = getattr(obj, attr)
            if callable(fn):
                self._handlers[f"{name}.{attr}"] = fn

    def register_function(self, name: str, fn) -> None:
        self._handlers[name] = fn

    @property
    def methods(self) -> list[str]:
        return sorted(self._handlers)

    # ------------------------------------------------------------- serving

    @property
    def serving(self) -> bool:
        return self._thread is not None and not self._closed.is_set()

    def serve(self) -> "ActorServer":
        """Start serving in the background; returns self."""
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._accept_loop, name=f"actor-{self.port}", daemon=True
        )
        self._thread.start()
        with _local_lock:
            _local_servers[(self.host, self.port)] = self
            # Alias every address a registry entry might advertise for this
            # server, so in-process clients short-circuit regardless of
            # which name they dial.
            _local_servers[("127.0.0.1", self.port)] = self
            from ptype_tpu.cluster import get_ip

            _local_servers[(get_ip(), self.port)] = self
        log.info("actor server listening",
                 kv={"addr": f"{self.host}:{self.port}",
                     "methods": len(self._handlers)})
        return self

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, peer = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name=f"actor-conn-{peer[1]}", daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        send_lock = threading.Lock()
        try:
            while not self._closed.is_set():
                try:
                    msg = wire.recv_msg(conn)
                except (wire.WireError, OSError):
                    return
                args_blob = None
                if msg.get("args_len"):
                    try:
                        args_blob = wire._recv_exact(conn, msg["args_len"])
                    except (wire.WireError, OSError):
                        return
                # net/rpc services requests concurrently; so do we.
                threading.Thread(
                    target=self._handle_request,
                    args=(conn, send_lock, msg, args_blob),
                    daemon=True,
                ).start()
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle_request(self, conn, send_lock, msg: dict, args_blob) -> None:
        req_id = msg.get("id")
        method = msg.get("method", "")
        try:
            args = codec.decode(args_blob) if args_blob is not None else ()
            # Adopt the caller's trace context (the "tp" frame field)
            # so dispatch()'s handler span joins the caller's trace —
            # the cross-process stitch.
            with trace.attach(msg.get("tp")):
                result = self.dispatch(method, args)
            result_parts = codec.encode_parts(result)
            reply = {"id": req_id, "ok": True,
                     "result_len": sum(len(p) for p in result_parts)}
        except ShedError as e:
            # Typed admission refusal: marshal the shed flag + retry
            # hint so the client re-raises a ShedError (and skips its
            # retry loop) instead of a generic RemoteError.
            reply = {"id": req_id, "ok": False, "shed": True,
                     "retry_after_s": e.retry_after_s, "error": str(e)}
            result_parts = []
        except Exception as e:  # noqa: BLE001 — server must not die
            reply = {"id": req_id, "ok": False, "error": f"{type(e).__name__}: {e}",
                     "traceback": traceback.format_exc()}
            result_parts = []
            # An unhandled handler error is a post-mortem moment:
            # snapshot the flight recorder (no-op unless a dump dir is
            # configured; rate-limited inside).
            trace.maybe_dump(f"actor error in {method}: "
                             f"{type(e).__name__}")
        try:
            payload = json.dumps(reply, separators=(",", ":")).encode()
            # One writev (native) / one sendall keeps the header frame and
            # result blobs adjacent without a concatenation copy.
            from ptype_tpu import native

            with send_lock:
                if not native.send_frame(conn, payload, result_parts):
                    conn.sendall(struct.pack(">I", len(payload)) + payload
                                 + b"".join(result_parts))
        except OSError:
            pass

    def dispatch(self, method: str, args):
        """Invoke a handler directly (used by the zero-copy local path).

        The handler runs inside an ``actor/<method>`` span — for wire
        calls it parents under the traceparent `_handle_request`
        attached; for local calls the caller's context flows in via
        `_LocalConn`'s copied contextvars. Both paths stitch."""
        fn = self._handlers.get(method)
        if fn is None:
            raise AttributeError(f"no such method: {method!r}")
        with trace.span(f"actor/{method}", port=self.port):
            if isinstance(args, (list, tuple)):
                return fn(*args)
            return fn(args)

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        with _local_lock:
            for key in [k for k, v in _local_servers.items() if v is self]:
                del _local_servers[key]
        # shutdown() before close(): threads parked in accept(2)/recv(2)
        # are not woken by close() alone — without this, every conn
        # reader (and the accept loop) outlives the server as a wedged
        # daemon thread.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
