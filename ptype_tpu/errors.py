"""Typed errors for ptype_tpu.

The reference exposes two sentinel errors: ``ErrNoKey``
(cluster/store.go:15) and ``ErrNoClientAvailable`` (cluster/rpc.go:15).
Python idiom is exception *classes*; we provide those plus aliases with the
reference names so ported call-sites read naturally.
"""


class ClusterError(Exception):
    """Base class for every error raised by ptype_tpu."""


class ConfigError(ClusterError):
    """Configuration file missing, unparseable, or invalid."""


class NoKeyError(ClusterError, KeyError):
    """Key could not be found (ref: cluster/store.go:15)."""

    def __init__(self, key: str = ""):
        super().__init__(key)
        self.key = key

    def __str__(self) -> str:  # KeyError quotes its arg; keep a message
        return f"key could not be found: {self.key!r}"


class RPCError(ClusterError):
    """An actor call failed (transport or remote handler error)."""


class RemoteError(RPCError):
    """The remote handler raised; carries the remote traceback text."""

    def __init__(self, message: str, remote_traceback: str = ""):
        super().__init__(message)
        self.remote_traceback = remote_traceback


class NoClientAvailableError(RPCError):
    """No client nodes available (ref: cluster/rpc.go:15)."""


class ShedError(RPCError):
    """The inference gateway refused admission (overload / deadline).

    A typed, *terminal* RPC error: the RPC client surfaces it without
    retrying (re-firing into an overloaded service amplifies the
    overload), and it round-trips the actor wire with its retry hint
    intact (actor.py marshals it, rpc.py re-raises it typed). Callers
    back off ``retry_after_s`` and try again.
    """

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class LeaseExpiredError(ClusterError):
    """A lease-backed registration expired and was not renewed."""


class CoordinationError(ClusterError):
    """The coordination service is unreachable or rejected a request."""


class MeshError(ClusterError):
    """Device-mesh construction or sharding binding failed."""


class CheckpointError(ClusterError):
    """Checkpoint save/restore failed."""


# Reference-named aliases (Go sentinel-error spelling).
ErrNoKey = NoKeyError
ErrNoClientAvailable = NoClientAvailableError
