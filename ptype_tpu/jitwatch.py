"""Runtime recompile watchdog + transfer discipline — the dynamic half
of the dispatch-discipline plane (ptlint PT018–PT020 are the static
passes, :mod:`ptype_tpu.progaudit` the program contract).

A retrace hazard that slips past the lint — a dtype that flaps between
weak and strong, a shape that wobbles, a function object rebuilt per
call — shows up at runtime as the SAME program compiling again with
the SAME signature. jax logs every backend compile when
``jax_log_compiles`` is on; this module hooks that seam and keeps
per-function books:

- **disarmed** (default): no jax config touched, zero cost — the
  factory pattern of :mod:`ptype_tpu.lockcheck`;
- **armed** (:func:`enable`, or ``PTYPE_JITWATCH=1`` at import):
  every backend compile is counted per ``(function, signature)``. A
  compile of a signature already compiled is a **recompile** — the
  cache SHOULD have hit — and bumps the ``jit.recompiles`` counter
  plus a per-function ``jit.fn.<name>`` gauge (bounded by the
  function-name universe, like lockcheck's lock names), which the
  health sampler turns into the series the ``recompile-storm`` rule
  pages on, NAMING the function. A storm (the same signature
  compiled ≥ ``storm_threshold`` times) dumps through the flight
  recorder the moment it is detected.

Transfer discipline rides along: :func:`hot_region` arms
``jax.transfer_guard`` (host→device AND device→host, implicit
transfers only) around a hot dispatch region — a numpy array or
python scalar smuggled into a jitted call raises AT THE CALL instead
of silently re-uploading per step. :func:`sanctioned_transfer` is the
typed exemption seam for the places a transfer IS the contract (the
train data leg, a meter's host sync); every pass through it is
counted (``jit.sanctioned_transfers``), so "zero *unsanctioned*
transfers" is enforced by construction inside armed regions.

Steady-state contract for the armed test tiers (chaos soak, serve,
train): warm up, :func:`mark_steady`, run the loop, then
``recompiles_since_steady() == {}`` — a steady-state engine compiles
NOTHING.

Stdlib-only at import; jax is touched only by :func:`enable` and the
armed guards (a lean coordinator process never pays the import).
"""

from __future__ import annotations

import contextlib
import logging
import os
import re
import threading
import time

__all__ = [
    "enable", "disable", "active", "JitWatch", "hot_region",
    "sanctioned_transfer", "ENV_VAR", "TRANSFER_ENV_VAR",
    "STORM_ENV_VAR",
]

ENV_VAR = "PTYPE_JITWATCH"
#: Guard level for hot regions: "disallow" (default — an unsanctioned
#: implicit transfer raises), "log", or "off" (recompile counting
#: only).
TRANSFER_ENV_VAR = "PTYPE_JITWATCH_TRANSFERS"
STORM_ENV_VAR = "PTYPE_JITWATCH_STORM"
DEFAULT_STORM_THRESHOLD = 3

#: The pxla compile log line: "Compiling <name> with global shapes and
#: types [...]. Argument mapping: (...)." — one WARNING per backend
#: compile (i.e. per trace-cache miss). The SIGNATURE is shapes+types
#: AND the argument mapping: the same shapes under different
#: shardings are legitimately distinct programs, not a recompile.
_COMPILE_RE = re.compile(
    r"Compiling (\S+) with global shapes and types (.*?Argument "
    r"mapping:.*)$", re.DOTALL)
_COMPILE_LOGGER = "jax._src.interpreters.pxla"


class _CompileFilter(logging.Filter):
    """Feeds parsed compile records into the watchdog. Installed as a
    logging FILTER (not a handler): when ``swallow`` is set — we
    armed ``jax_log_compiles`` ourselves, for the hook, not the
    console — the record is consumed here and never reaches any
    handler; an operator who had compile logs on already keeps
    them."""

    def __init__(self, watch: "JitWatch", swallow: bool):
        super().__init__()
        self._watch = watch
        self._swallow = swallow

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            m = _COMPILE_RE.search(record.getMessage())
            if m is not None:
                self._watch.on_compile(m.group(1), m.group(2))
        except Exception:  # noqa: BLE001 — a watchdog must never
            pass           # break the dispatch it watches
        return not self._swallow


class JitWatch:
    """Per-process compile books + steady-state marking."""

    def __init__(self, storm_threshold: int | None = None,
                 transfer_level: str | None = None,
                 ignored_fns: frozenset | None = None):
        if storm_threshold is None:
            storm_threshold = int(os.environ.get(
                STORM_ENV_VAR, DEFAULT_STORM_THRESHOLD))
        self.storm_threshold = int(storm_threshold)
        self.transfer_level = (transfer_level
                               or os.environ.get(TRANSFER_ENV_VAR,
                                                 "disallow"))
        #: jax's EAGER op-dispatch wrappers (jit(broadcast_in_dim),
        #: jit(convert_element_type) ...) legitimately re-compile the
        #: same input signature with different STATIC params — the
        #: log line can't tell those apart, so they are excluded from
        #: the recompile/storm books (raw compiles still counted).
        self.ignored_fns = (ignored_fns if ignored_fns is not None
                            else frozenset())
        self._mu = threading.Lock()
        #: (fn, signature) -> compile count. Distinct signatures are
        #: legit specializations (a new prefill chunk width); the SAME
        #: signature compiling twice means the cache was re-keyed.
        self._sigs: dict[tuple[str, str], int] = {}
        self._fn_compiles: dict[str, int] = {}
        self._fn_recompiles: dict[str, int] = {}
        self._storms: list[dict] = []
        self._steady_at: float | None = None
        self._steady_since: dict[str, int] = {}
        self._sanctioned: dict[str, int] = {}
        self._hot_regions = 0

    # -------------------------------------------------------- tracking

    def _is_internal(self, fn_name: str) -> bool:
        return fn_name.startswith("_") or fn_name in self.ignored_fns

    def on_compile(self, fn_name: str, signature: str) -> None:
        storm = None
        internal = self._is_internal(fn_name)
        with self._mu:
            key = (fn_name, signature)
            n = self._sigs.get(key, 0) + 1
            self._sigs[key] = n
            self._fn_compiles[fn_name] = \
                self._fn_compiles.get(fn_name, 0) + 1
            if self._steady_at is not None:
                self._steady_since[fn_name] = \
                    self._steady_since.get(fn_name, 0) + 1
            recompile = n > 1 and not internal
            if recompile:
                self._fn_recompiles[fn_name] = \
                    self._fn_recompiles.get(fn_name, 0) + 1
            if n == self.storm_threshold and not internal:
                storm = {
                    "kind": "recompile-storm", "fn": fn_name,
                    "signature": signature[:256], "compiles": n,
                    "thread": threading.current_thread().name,
                    "t": time.time(),
                }
                self._storms.append(storm)
        self._publish(fn_name, recompile)
        if storm is not None:
            self._emit(storm)

    def _publish(self, fn_name: str, recompile: bool) -> None:
        """Metric families the sampler serializes and the
        recompile-storm rule / ``obs jit`` read. Lazy metrics import:
        the watchdog must stay importable below everything."""
        try:
            from ptype_tpu.metrics import metrics

            metrics.counter("jit.compiles").add(1)
            if recompile:
                metrics.counter("jit.recompiles").add(1)
                with self._mu:
                    count = self._fn_recompiles.get(fn_name, 0)
                metrics.gauge(f"jit.fn.{fn_name}").set(float(count))
        except Exception:  # noqa: BLE001 — never break a compile
            pass

    @staticmethod
    def _emit(finding: dict) -> None:
        """Flight-recorder seam (the lockcheck pattern): an event on
        the active span plus a rate-limited ring dump naming the
        function — the post-mortem artifact."""
        try:
            from ptype_tpu import trace

            trace.add_event("jitwatch.storm",
                            **{k: str(v) for k, v in finding.items()
                               if k not in ("kind", "t")})
            trace.maybe_dump(
                f"recompile-storm: {finding['fn']} compiled "
                f"{finding['compiles']}x with one signature")
        except Exception:  # noqa: BLE001
            pass

    def note_sanctioned(self, reason: str) -> None:
        with self._mu:
            self._sanctioned[reason] = \
                self._sanctioned.get(reason, 0) + 1
        try:
            from ptype_tpu.metrics import metrics

            metrics.counter("jit.sanctioned_transfers").add(1)
        except Exception:  # noqa: BLE001
            pass

    def note_hot_region(self) -> None:
        with self._mu:
            self._hot_regions += 1

    # ------------------------------------------------------ steady state

    def mark_steady(self) -> None:
        """Warmup is over: every compile FROM NOW ON is a steady-state
        discipline violation (``recompiles_since_steady``)."""
        with self._mu:
            self._steady_at = time.time()
            self._steady_since = {}

    def recompiles_since_steady(self) -> dict[str, int]:
        """fn -> compiles (of ANY signature) since ``mark_steady`` —
        the armed tiers assert this is ``{}``: a steady-state hot loop
        compiles nothing, new shape or not."""
        with self._mu:
            return dict(self._steady_since)

    # ------------------------------------------------------ inspection

    def compiles(self) -> dict[str, int]:
        with self._mu:
            return dict(self._fn_compiles)

    def recompiles(self) -> dict[str, int]:
        """fn -> same-signature recompile count (compiles the cache
        should have served)."""
        with self._mu:
            return dict(self._fn_recompiles)

    def storms(self) -> list[dict]:
        with self._mu:
            return list(self._storms)

    def sanctioned(self) -> dict[str, int]:
        with self._mu:
            return dict(self._sanctioned)

    def report(self) -> dict:
        with self._mu:
            return {
                "compiles": dict(self._fn_compiles),
                "recompiles": dict(self._fn_recompiles),
                "signatures": len(self._sigs),
                "storms": list(self._storms),
                "storm_threshold": self.storm_threshold,
                "steady_since": dict(self._steady_since),
                "steady_marked": self._steady_at is not None,
                "sanctioned_transfers": dict(self._sanctioned),
                "hot_regions": self._hot_regions,
                "transfer_level": self.transfer_level,
            }


# ------------------------------------------------------------ module API

_watch: JitWatch | None = None
_filters: list[tuple[str, logging.Filter]] = []
_prior_log_compiles: bool | None = None
#: Loggers jax_log_compiles elevates to WARNING. The pxla one carries
#: the "Compiling <fn> ..." line the hook parses; the dispatch one is
#: pure timing noise — both are swallowed while WE armed the config.
_NOISY_LOGGERS = ("jax._src.dispatch", _COMPILE_LOGGER)


def _eager_wrapper_names() -> frozenset:
    """Public jax.lax / jax.numpy names: the functions jax's EAGER op
    dispatch compiles under (``jit(broadcast_in_dim)`` on a concrete
    array). Bounded, computed once per enable."""
    import jax
    import jax.numpy as jnp

    return frozenset(n for n in dir(jax.lax) + dir(jnp)
                     if not n.startswith("_"))


def enable(storm_threshold: int | None = None,
           transfer_level: str | None = None) -> JitWatch:
    """Arm the watchdog process-wide: turns ``jax_log_compiles`` on
    and hooks the compile-log seam. Re-enabling replaces the books.
    Returns the fresh watchdog."""
    global _watch, _prior_log_compiles
    import jax

    disable()
    _watch = JitWatch(storm_threshold, transfer_level,
                      ignored_fns=_eager_wrapper_names())
    _prior_log_compiles = bool(jax.config.jax_log_compiles)
    jax.config.update("jax_log_compiles", True)
    swallow = not _prior_log_compiles
    for name in _NOISY_LOGGERS:
        filt = _CompileFilter(_watch, swallow)
        logging.getLogger(name).addFilter(filt)
        _filters.append((name, filt))
    return _watch


def disable() -> None:
    """Disarm: detach the hook, restore the prior compile-log config."""
    global _watch, _prior_log_compiles
    for name, filt in _filters:
        logging.getLogger(name).removeFilter(filt)
    _filters.clear()
    if _prior_log_compiles is not None:
        try:
            import jax

            jax.config.update("jax_log_compiles",
                              _prior_log_compiles)
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
        _prior_log_compiles = None
    _watch = None


def active() -> JitWatch | None:
    return _watch


@contextlib.contextmanager
def hot_region(name: str):
    """Dispatch-discipline guard around a hot program call. Disarmed:
    free. Armed: ``jax.transfer_guard`` at the watchdog's level (the
    default ``disallow`` makes an unsanctioned IMPLICIT transfer —
    a numpy array or python scalar fed to a jitted call, a stray
    ``jnp.zeros`` constant — raise at the call site, naming it),
    explicit transfers (``jnp.asarray``/``device_put``/the engine's
    metered host syncs) stay legal. ``name`` is for the books."""
    w = _watch
    if w is None or w.transfer_level in ("off", ""):
        yield
        return
    import jax

    w.note_hot_region()
    with jax.transfer_guard_host_to_device(w.transfer_level), \
            jax.transfer_guard_device_to_host(w.transfer_level):
        yield


@contextlib.contextmanager
def sanctioned_transfer(reason: str):
    """The typed exemption seam: a region where a transfer IS the
    contract (the train data leg, a meter host sync). Counted per
    pass (``jit.sanctioned_transfers`` + per-reason books) so the
    exemptions stay auditable."""
    w = _watch
    if w is None:
        yield
        return
    import jax

    w.note_sanctioned(reason)
    with jax.transfer_guard("allow"):
        yield


def _maybe_enable_from_env() -> None:
    if os.environ.get(ENV_VAR, "").lower() in ("1", "true", "on"):
        enable()


_maybe_enable_from_env()
