"""Metrics / observability.

The reference had logging only — zap globals, no metrics surface
(SURVEY.md §5 "Metrics": `Client.ConnectionErrs` was the entire
observability API, cluster/rpc.go:122-124). The BASELINE.json metrics
(tokens/sec/chip, MFU, collective GB/s) need a real counter/timing
module; this is it.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from dataclasses import dataclass, field

from ptype_tpu import lockcheck

import jax

from ptype_tpu import trace as trace_mod

#: Peak bf16 matmul TFLOP/s per chip, by PJRT device_kind substring.
#: Public numbers (cloud.google.com/tpu docs); CPU entry is a nominal
#: figure so MFU stays defined (and obviously tiny) in CPU test runs.
PEAK_TFLOPS = {
    "v6e": 918.0,
    "v5p": 459.0,
    "v5e": 197.0,  # v5 litepod
    "v5": 197.0,
    "v4": 275.0,
    "v3": 123.0,
    "v2": 45.0,
    "cpu": 0.5,
}

#: Env override for the peak table: either one bare float (the peak
#: for whatever chip this process sees — the operator knows better
#: than the substring table) or ``kind=tflops`` pairs merged over it
#: (``"trillium=918,v7=2000"``). A new chip generation must be one
#: env var away from a correct MFU, not a code change.
PEAK_TFLOPS_ENV = "PTYPE_PEAK_TFLOPS"

#: Process-level override (set_peak_tflops) — wins over env and table.
_peak_override: float | None = None
#: device_kinds already warned about — the unknown-platform fallback
#: logs ONCE per kind, not once per MFU computation.
_peak_warned: set = set()


def set_peak_tflops(value: float | None) -> None:
    """Pin (or clear, with ``None``) the per-chip peak used by every
    MFU computation in this process — the config-file seam; the env
    seam is :data:`PEAK_TFLOPS_ENV`."""
    global _peak_override
    _peak_override = None if value is None else float(value)


def _peak_env() -> tuple[float | None, dict]:
    """(flat override, table additions) parsed from the env var;
    malformed entries are ignored (a typo must not break MFU)."""
    import os

    raw = os.environ.get(PEAK_TFLOPS_ENV, "").strip()
    if not raw:
        return None, {}
    extra: dict = {}
    flat = None
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            key, _, val = part.partition("=")
            try:
                extra[key.strip().lower()] = float(val)
            except ValueError:
                pass
        else:
            try:
                flat = float(part)
            except ValueError:
                pass
    return flat, extra


def device_peak_tflops(device=None) -> float:
    """Best-effort peak bf16 TFLOP/s for a device (default:
    devices()[0]). Resolution order: :func:`set_peak_tflops` override,
    a bare-float :data:`PEAK_TFLOPS_ENV`, then the device_kind
    substring table (env ``kind=value`` pairs take precedence within
    it). An UNKNOWN non-CPU platform falls back to the v5e figure and
    logs once per kind — MFU is never quietly computed against a
    wrong peak without a trail."""
    if _peak_override is not None:
        return _peak_override
    flat, extra = _peak_env()
    if flat is not None:
        return flat
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "") or device.platform
    kind = kind.lower()
    for key, tf in extra.items():
        if key in kind:
            return tf
    for key, tf in PEAK_TFLOPS.items():
        if key in kind:
            return tf
    if device.platform == "cpu":
        return PEAK_TFLOPS["cpu"]
    if kind not in _peak_warned:
        _peak_warned.add(kind)
        from ptype_tpu import logs

        logs.get_logger("metrics").warning(
            "unknown accelerator kind; MFU will use the v5e peak — "
            "override with the env table",
            kv={"device_kind": kind, "fallback_tflops":
                PEAK_TFLOPS["v5e"], "env": PEAK_TFLOPS_ENV})
    return PEAK_TFLOPS["v5e"]


def mfu(tokens_per_sec: float, flops_per_token: float,
        n_chips: int, peak_tflops: float | None = None) -> float:
    """Model FLOPs utilization in [0, 1]: achieved / peak."""
    peak = (peak_tflops or device_peak_tflops()) * 1e12 * n_chips
    return tokens_per_sec * flops_per_token / peak


#: Samples a Counter keeps for its windowed rate() — filled by the
#: health sampler's cadence (one sample per tick), sized so a minute
#: of 1 Hz sampling fits.
COUNTER_RATE_WINDOW = 64


@dataclass
class Counter:
    name: str
    value: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)
    #: (t, cumulative value) samples behind the windowed rate() — the
    #: hot-path add() never touches this; the health Sampler (or an
    #: explicit sample() call) stamps it at its cadence.
    _samples: collections.deque = field(
        default_factory=lambda: collections.deque(
            maxlen=COUNTER_RATE_WINDOW),
        repr=False, compare=False)

    def add(self, delta: float = 1.0) -> None:
        with self._lock:
            self.value += delta

    def sample(self, now: float | None = None) -> None:
        """Stamp (t, value) into the rate window — called by the health
        sampler at its cadence (time.monotonic clock)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._samples.append((now, self.value))

    def rate(self, window_s: float | None = None,
             now: float | None = None) -> float:
        """Events/sec over the sampled window (the sampler cadence).

        Computed from the stamped samples only — deterministic under
        explicit sample(now=...) calls. With a single sample the live
        value at ``now`` closes the interval; with none, 0.0."""
        now = time.monotonic() if now is None else now
        with self._lock:
            pts = list(self._samples)
            cur = self.value
        if window_s is not None:
            pts = [p for p in pts if p[0] >= now - window_s]
        if not pts:
            return 0.0
        t0, v0 = pts[0]
        t1, v1 = pts[-1] if len(pts) > 1 else (now, cur)
        if t1 <= t0:
            return 0.0
        return max(0.0, (v1 - v0) / (t1 - t0))


#: Recent observations a Timing keeps for its percentile window —
#: enough to be distribution-aware on hot paths, small enough that the
#: per-observe cost stays one deque append.
TIMING_WINDOW = 256


@dataclass
class Timing:
    name: str
    total: float = 0.0
    count: int = 0
    #: Most recent observation — what a bench tail or debugger wants
    #: from a warm path (the mean is polluted by the compile-pass
    #: first observation).
    last: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)
    #: Ring of the most recent observations, powering percentile() —
    #: hot-path timings (rpc calls, store pushes) are long-tailed, and
    #: a mean hides exactly the tail an SLO check needs.
    _recent: collections.deque = field(
        default_factory=lambda: collections.deque(maxlen=TIMING_WINDOW),
        repr=False, compare=False)

    def observe(self, seconds: float) -> None:
        with self._lock:
            self.total += seconds
            self.count += 1
            self.last = seconds
            self._recent.append(seconds)

    @property
    def mean(self) -> float:
        with self._lock:
            return self.total / self.count if self.count else 0.0

    @staticmethod
    def _rank(data: list, p: float) -> float:
        if not data:
            return 0.0
        rank = max(0, min(len(data) - 1,
                          int(round(p / 100.0 * (len(data) - 1)))))
        return data[rank]

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the recent window (seconds);
        0.0 before any observation."""
        with self._lock:
            data = sorted(self._recent)
        return self._rank(data, p)

    def summary(self) -> dict:
        # One lock round-trip + one sort for all three percentiles:
        # snapshot() calls this per timing on every ptype.Telemetry
        # pull, and observe() contends the same lock on hot paths.
        with self._lock:
            data = sorted(self._recent)
            total, count, last = self.total, self.count, self.last
        return {"mean_s": total / count if count else 0.0,
                "count": count, "last_s": last,
                "p50_s": self._rank(data, 50.0),
                "p95_s": self._rank(data, 95.0),
                "p99_s": self._rank(data, 99.0)}


@dataclass
class Gauge:
    """A last-write-wins level (queue depth, live replicas, scale
    hint) — the counter/timing pair can't express 'current value'."""

    name: str
    value: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def add(self, delta: float = 1.0) -> None:
        with self._lock:
            self.value += delta


#: Exemplar slots kept per histogram: the K worst observations that
#: arrived with a trace id attached. Small and fixed — the point is a
#: handful of replayable links off the p99, not a second reservoir.
EXEMPLAR_SLOTS = 8


class Histogram:
    """Windowed reservoir with exact percentiles over the last
    ``window`` observations — the tail-latency surface (p50/p95/p99)
    the gateway's SLO accounting and autoscale signals read. A ring
    buffer, not a sketch: serving windows are small (thousands), and
    exact tails are what an SLO check needs.

    **Exemplars** (ISSUE 20): when an observation happens inside an
    active trace (or the caller passes ``trace_id``), the value keeps
    its trace id in one of :data:`EXEMPLAR_SLOTS` worst-value slots —
    so the p99 a dashboard shows links to a real replayable trace in
    the flight recorder, not an anonymous number. Free when tracing
    is disabled (one global load in :func:`trace.current_trace_id`)."""

    __slots__ = ("name", "window", "_ring", "_idx", "_count", "_lock",
                 "_exemplars")

    def __init__(self, name: str, window: int = 2048):
        self.name = name
        self.window = int(window)
        self._ring: list[float] = []
        self._idx = 0
        self._count = 0
        self._exemplars: list[tuple[float, str, float]] = []
        self._lock = lockcheck.lock("metrics.histogram")

    def observe(self, value: float, trace_id: str | None = None) -> None:
        v = float(value)
        if trace_id is None:
            trace_id = trace_mod.current_trace_id()
        with self._lock:
            if len(self._ring) < self.window:
                self._ring.append(v)
            else:
                self._ring[self._idx] = v
                self._idx = (self._idx + 1) % self.window
            self._count += 1
            if trace_id:
                ex = self._exemplars
                if len(ex) < EXEMPLAR_SLOTS:
                    ex.append((v, trace_id, time.time()))
                else:
                    i = min(range(len(ex)), key=lambda j: ex[j][0])
                    if v > ex[i][0]:
                        ex[i] = (v, trace_id, time.time())

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the window; 0.0 when empty."""
        with self._lock:
            data = sorted(self._ring)
        if not data:
            return 0.0
        rank = max(0, min(len(data) - 1,
                          int(round(p / 100.0 * (len(data) - 1)))))
        return data[rank]

    def exemplars(self) -> list[dict]:
        """Worst-first ``{value, trace_id, ts}`` exemplar slots —
        what ``obs tail`` and the OpenMetrics exporter surface."""
        with self._lock:
            ex = list(self._exemplars)
        ex.sort(key=lambda e: -e[0])
        return [{"value": round(v, 3), "trace_id": tid,
                 "ts": round(ts, 3)} for v, tid, ts in ex]

    def summary(self) -> dict:
        out = {"count": self.count,
               "p50": self.percentile(50.0),
               "p95": self.percentile(95.0),
               "p99": self.percentile(99.0)}
        ex = self.exemplars()
        if ex:  # key present only when real links exist — snapshot
            out["exemplars"] = ex  # shape is pinned by older tests
        return out


class MetricsRegistry:
    """Process-local named counters/timings with a JSON dump — the
    metrics surface the reference never had (SURVEY.md §5)."""

    def __init__(self):
        self._counters: dict[str, Counter] = {}
        self._timings: dict[str, Timing] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self._lock = lockcheck.lock("metrics.registry")
        self._version = 0

    def _family(self, fam: dict, name: str, make):
        with self._lock:
            obj = fam.get(name)
            if obj is None:
                obj = fam[name] = make()
                # Version bumps let the health Sampler cache its walk
                # list and stay allocation-free between new families.
                self._version += 1
            return obj

    def counter(self, name: str) -> Counter:
        return self._family(self._counters, name, lambda: Counter(name))

    def timing(self, name: str) -> Timing:
        return self._family(self._timings, name, lambda: Timing(name))

    def gauge(self, name: str) -> Gauge:
        return self._family(self._gauges, name, lambda: Gauge(name))

    def histogram(self, name: str, window: int = 2048) -> Histogram:
        return self._family(self._histograms, name,
                            lambda: Histogram(name, window))

    @property
    def version(self) -> int:
        """Bumped once per family creation — the sampler's cheap
        'did the registry grow since my cached walk list' check."""
        with self._lock:
            return self._version

    def families(self) -> tuple:
        """(version, counters, timings, gauges, histograms) — shallow
        copies of the live family maps, for consumers (the health
        sampler) that need values-and-counts without the full summary
        construction :meth:`snapshot` pays."""
        with self._lock:
            return (self._version, dict(self._counters),
                    dict(self._timings), dict(self._gauges),
                    dict(self._histograms))

    def timed(self, name: str):
        """Context manager recording wall time into a Timing."""
        registry = self

        class _Ctx:
            def __enter__(self):
                self._t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                registry.timing(name).observe(time.perf_counter() - self._t0)
                return False

        return _Ctx()

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            timings = dict(self._timings)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        # Every family dumps uniformly: counters/gauges as values,
        # timings and histograms as distribution summaries (count +
        # p50/p95/p99) — the gateway's SLO tail and a hot path's
        # Timing read the same way in one dump.
        return {
            "counters": {n: c.value for n, c in counters.items()},
            "timings": {n: t.summary() for n, t in timings.items()},
            "gauges": {n: g.value for n, g in gauges.items()},
            "histograms": {n: h.summary() for n, h in histograms.items()},
        }

    def dump_json(self) -> str:
        return json.dumps(self.snapshot(), separators=(",", ":"))


#: Default process-global registry.
metrics = MetricsRegistry()


def flatten_snapshot(snap: dict) -> dict:
    """One flat ``{name: scalar}`` view of a registry snapshot — what
    :meth:`MetricsWriter.emit` merges so the training scalar log and
    the health-plane series read the same values: counters and gauges
    as-is, timings as ``<name>.last_s`` (what the sampler stamps into
    its series) plus ``<name>.mean_s``, histograms as ``<name>.p99``.
    """
    flat: dict = {}
    flat.update(snap.get("counters", {}))
    flat.update(snap.get("gauges", {}))
    for name, s in snap.get("timings", {}).items():
        flat[f"{name}.last_s"] = s.get("last_s", 0.0)
        flat[f"{name}.mean_s"] = s.get("mean_s", 0.0)
    for name, s in snap.get("histograms", {}).items():
        flat[f"{name}.p99"] = s.get("p99", 0.0)
    return flat


# --------------------------------------------------------- memory gauges


def memory_watermarks(device=None) -> dict:
    """Device HBM watermarks where the backend reports them
    (``device.memory_stats()``: bytes_in_use / peak_bytes_in_use, the
    PJRT allocator's numbers), plus the process peak RSS fallback via
    ``resource.getrusage`` — always present, so the health plane can
    watch memory growth even on backends with no allocator stats."""
    out: dict = {}
    try:
        dev = device if device is not None else jax.devices()[0]
        stats = dev.memory_stats() or {}
    except Exception:  # noqa: BLE001 — stats are best-effort per backend
        stats = {}
    for src, dst in (("bytes_in_use", "device_bytes_in_use"),
                     ("peak_bytes_in_use", "device_peak_bytes"),
                     ("bytes_limit", "device_bytes_limit")):
        if src in stats:
            out[dst] = int(stats[src])
    try:
        import resource

        # Linux reports ru_maxrss in KiB; it is a peak, i.e. already a
        # watermark.
        out["rss_bytes"] = int(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024
    except Exception:  # noqa: BLE001 — resource is POSIX-only
        pass
    return out


def record_memory_gauges(registry: MetricsRegistry | None = None) -> dict:
    """Refresh the ``mem.*`` gauges from :func:`memory_watermarks` in
    ``registry`` (default: the process-global one) and return the raw
    dict — the seam serve.Info(), the telemetry endpoint, and the
    health sampler share."""
    reg = registry if registry is not None else metrics
    wm = memory_watermarks()
    for key, value in wm.items():
        reg.gauge(f"mem.{key}").set(value)
    return wm


class MetricsWriter:
    """Append-only JSONL metrics sink for training runs.

    One ``{"ts": ..., "step": ..., **scalars}`` line per emit —
    tail-able during a run, trivially loadable after (pandas/jq); the
    file-based observability tier beneath profiler traces. Flushed per
    line so a SIGKILLed run keeps everything emitted before the kill.
    """

    def __init__(self, path: str):
        import os

        os.makedirs(os.path.dirname(os.path.abspath(path)),
                    exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._lock = lockcheck.lock("metrics.kvlogger")

    def emit(self, step: int, snapshot: dict | None = None,
             **scalars) -> None:
        """Emit one line. ``snapshot`` (a :meth:`MetricsRegistry
        .snapshot` dict, or a registry to snapshot) merges flattened
        via :func:`flatten_snapshot` UNDER the explicit scalars — the
        training log and the health series then agree on one source of
        truth instead of call sites recomputing rates by hand."""
        import math

        if snapshot is not None:
            if isinstance(snapshot, MetricsRegistry):
                snapshot = snapshot.snapshot()
            merged = flatten_snapshot(snapshot)
            merged.update(scalars)
            scalars = merged
        rec = {"ts": round(time.time(), 3), "step": int(step)}
        for k, v in scalars.items():
            try:
                f = float(v)
            except (TypeError, ValueError):
                rec[k] = str(v)
                continue
            # json.dumps would emit the invalid-JSON token `NaN` and
            # break jq/strict parsers on exactly the diverging runs
            # where the file matters most — stringify non-finite.
            rec[k] = f if math.isfinite(f) else str(f)
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        with self._lock:
            self._f.write(line)
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


# ------------------------------------------------------------- profiling
# The reference had zap logging only (SURVEY.md §5 "Tracing/profiling:
# Absent"); the TPU build owes JAX profiler traces (XPlane/TensorBoard)
# with annotated steps so Store collective time is attributable.


class trace:
    """Context manager: capture a JAX profiler trace (XPlane) to
    ``logdir`` — view with TensorBoard's profile plugin or xprof.

    >>> with metrics.trace("/tmp/trace"):
    ...     trainer.step(batch)
    """

    def __init__(self, logdir: str):
        self.logdir = logdir

    def __enter__(self):
        jax.profiler.start_trace(self.logdir)
        return self

    def __exit__(self, *exc):
        jax.profiler.stop_trace()
        return False


#: Observer for finished annotate() regions — ``fn(name, dur_s)``.
#: The health plane's goodput ledger installs itself here, so every
#: train.step / store.push_tree / checkpoint region feeds the per-step
#: breakdown through the one existing seam.
_annotate_observer = None


def set_annotate_observer(fn) -> None:
    """Install (or clear, with ``None``) the region observer. One
    observer per process — the goodput ledger; tests that need several
    ledgers drive them directly via ``GoodputLedger.region``."""
    global _annotate_observer
    _annotate_observer = fn


class _AnnotatedSpan:
    """TraceAnnotation + distributed-trace span + region observer
    entered as one scope — profiler timelines, the flight recorder,
    and the goodput ledger see the same region."""

    __slots__ = ("_ann", "_sp", "_name", "_obs", "_t0")

    def __init__(self, ann, sp, name, obs):
        self._ann = ann
        self._sp = sp
        self._name = name
        self._obs = obs

    def __enter__(self):
        self._ann.__enter__()
        self._sp.__enter__()
        if self._obs is not None:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._obs is not None:
            dt = time.perf_counter() - self._t0
            try:
                self._obs(self._name, dt)
            except Exception:  # noqa: BLE001 — telemetry must never
                pass           # kill the training step it observes,
                #                nor leak the span/annotation scopes.
        self._sp.__exit__(*exc)
        return self._ann.__exit__(*exc)


def annotate(name: str, **kwargs):
    """Named region in profiler traces (host + device timeline). Use
    around Store pushes so allreduce time is attributable:

    >>> with metrics.annotate("store.push/grads"):
    ...     store.push_tree("grads", grads)

    When distributed tracing is armed (:mod:`ptype_tpu.trace`), the
    region ALSO opens a span of the same name — store pushes and train
    steps nest inside both the jax profiler trace and the request's
    distributed trace through this one seam. When a region observer is
    installed (:func:`set_annotate_observer` — the goodput ledger),
    the region's wall time is reported to it on exit. With neither
    armed the cost stays one ``enabled()`` check + one global load.
    """
    ann = jax.profiler.TraceAnnotation(name, **kwargs)
    obs = _annotate_observer
    if obs is None and not trace_mod.enabled():
        return ann
    return _AnnotatedSpan(ann, trace_mod.span(name), name, obs)


def step_annotation(step: int):
    """Mark one training step in the trace (XProf groups by these)."""
    return jax.profiler.StepTraceAnnotation("train", step_num=step)


@dataclass
class StepStats:
    """Rolling per-step throughput tracker for training loops."""

    flops_per_token: float
    n_chips: int
    peak_tflops: float | None = None
    tokens: int = 0
    seconds: float = 0.0
    steps: int = 0
    _t0: float = field(default=0.0, repr=False)

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def step(self, n_tokens: int, n_steps: int = 1) -> None:
        """Fold ``n_tokens`` of COMPLETED work (``n_steps`` train steps)
        into the rolling rates. Callers that dispatch asynchronously must
        only call this at drain boundaries — crediting tokens at dispatch
        time measures queueing rate, not compute (VERDICT r2 weak #5)."""
        now = time.perf_counter()
        self.seconds += now - self._t0
        self._t0 = now
        self.tokens += n_tokens
        self.steps += n_steps

    @property
    def tokens_per_sec(self) -> float:
        return self.tokens / self.seconds if self.seconds else 0.0

    @property
    def tokens_per_sec_per_chip(self) -> float:
        return self.tokens_per_sec / max(self.n_chips, 1)

    @property
    def mfu(self) -> float:
        return mfu(self.tokens_per_sec, self.flops_per_token,
                   self.n_chips, self.peak_tflops)
