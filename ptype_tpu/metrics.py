"""Metrics / observability.

The reference had logging only — zap globals, no metrics surface
(SURVEY.md §5 "Metrics": `Client.ConnectionErrs` was the entire
observability API, cluster/rpc.go:122-124). The BASELINE.json metrics
(tokens/sec/chip, MFU, collective GB/s) need a real counter/timing
module; this is it.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from dataclasses import dataclass, field

import jax

from ptype_tpu import trace as trace_mod

#: Peak bf16 matmul TFLOP/s per chip, by PJRT device_kind substring.
#: Public numbers (cloud.google.com/tpu docs); CPU entry is a nominal
#: figure so MFU stays defined (and obviously tiny) in CPU test runs.
PEAK_TFLOPS = {
    "v6e": 918.0,
    "v5p": 459.0,
    "v5e": 197.0,  # v5 litepod
    "v5": 197.0,
    "v4": 275.0,
    "v3": 123.0,
    "v2": 45.0,
    "cpu": 0.5,
}


def device_peak_tflops(device=None) -> float:
    """Best-effort peak bf16 TFLOP/s for a device (default: devices()[0])."""
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "") or device.platform
    kind = kind.lower()
    for key, tf in PEAK_TFLOPS.items():
        if key in kind:
            return tf
    return PEAK_TFLOPS["cpu"] if device.platform == "cpu" else 197.0


def mfu(tokens_per_sec: float, flops_per_token: float,
        n_chips: int, peak_tflops: float | None = None) -> float:
    """Model FLOPs utilization in [0, 1]: achieved / peak."""
    peak = (peak_tflops or device_peak_tflops()) * 1e12 * n_chips
    return tokens_per_sec * flops_per_token / peak


@dataclass
class Counter:
    name: str
    value: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)

    def add(self, delta: float = 1.0) -> None:
        with self._lock:
            self.value += delta


#: Recent observations a Timing keeps for its percentile window —
#: enough to be distribution-aware on hot paths, small enough that the
#: per-observe cost stays one deque append.
TIMING_WINDOW = 256


@dataclass
class Timing:
    name: str
    total: float = 0.0
    count: int = 0
    #: Most recent observation — what a bench tail or debugger wants
    #: from a warm path (the mean is polluted by the compile-pass
    #: first observation).
    last: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)
    #: Ring of the most recent observations, powering percentile() —
    #: hot-path timings (rpc calls, store pushes) are long-tailed, and
    #: a mean hides exactly the tail an SLO check needs.
    _recent: collections.deque = field(
        default_factory=lambda: collections.deque(maxlen=TIMING_WINDOW),
        repr=False, compare=False)

    def observe(self, seconds: float) -> None:
        with self._lock:
            self.total += seconds
            self.count += 1
            self.last = seconds
            self._recent.append(seconds)

    @property
    def mean(self) -> float:
        with self._lock:
            return self.total / self.count if self.count else 0.0

    @staticmethod
    def _rank(data: list, p: float) -> float:
        if not data:
            return 0.0
        rank = max(0, min(len(data) - 1,
                          int(round(p / 100.0 * (len(data) - 1)))))
        return data[rank]

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the recent window (seconds);
        0.0 before any observation."""
        with self._lock:
            data = sorted(self._recent)
        return self._rank(data, p)

    def summary(self) -> dict:
        # One lock round-trip + one sort for all three percentiles:
        # snapshot() calls this per timing on every ptype.Telemetry
        # pull, and observe() contends the same lock on hot paths.
        with self._lock:
            data = sorted(self._recent)
            total, count, last = self.total, self.count, self.last
        return {"mean_s": total / count if count else 0.0,
                "count": count, "last_s": last,
                "p50_s": self._rank(data, 50.0),
                "p95_s": self._rank(data, 95.0),
                "p99_s": self._rank(data, 99.0)}


@dataclass
class Gauge:
    """A last-write-wins level (queue depth, live replicas, scale
    hint) — the counter/timing pair can't express 'current value'."""

    name: str
    value: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def add(self, delta: float = 1.0) -> None:
        with self._lock:
            self.value += delta


class Histogram:
    """Windowed reservoir with exact percentiles over the last
    ``window`` observations — the tail-latency surface (p50/p95/p99)
    the gateway's SLO accounting and autoscale signals read. A ring
    buffer, not a sketch: serving windows are small (thousands), and
    exact tails are what an SLO check needs."""

    __slots__ = ("name", "window", "_ring", "_idx", "_count", "_lock")

    def __init__(self, name: str, window: int = 2048):
        self.name = name
        self.window = int(window)
        self._ring: list[float] = []
        self._idx = 0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            if len(self._ring) < self.window:
                self._ring.append(float(value))
            else:
                self._ring[self._idx] = float(value)
                self._idx = (self._idx + 1) % self.window
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the window; 0.0 when empty."""
        with self._lock:
            data = sorted(self._ring)
        if not data:
            return 0.0
        rank = max(0, min(len(data) - 1,
                          int(round(p / 100.0 * (len(data) - 1)))))
        return data[rank]

    def summary(self) -> dict:
        return {"count": self.count,
                "p50": self.percentile(50.0),
                "p95": self.percentile(95.0),
                "p99": self.percentile(99.0)}


class MetricsRegistry:
    """Process-local named counters/timings with a JSON dump — the
    metrics surface the reference never had (SURVEY.md §5)."""

    def __init__(self):
        self._counters: dict[str, Counter] = {}
        self._timings: dict[str, Timing] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter(name))

    def timing(self, name: str) -> Timing:
        with self._lock:
            return self._timings.setdefault(name, Timing(name))

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge(name))

    def histogram(self, name: str, window: int = 2048) -> Histogram:
        with self._lock:
            return self._histograms.setdefault(name,
                                               Histogram(name, window))

    def timed(self, name: str):
        """Context manager recording wall time into a Timing."""
        registry = self

        class _Ctx:
            def __enter__(self):
                self._t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                registry.timing(name).observe(time.perf_counter() - self._t0)
                return False

        return _Ctx()

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            timings = dict(self._timings)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        # Every family dumps uniformly: counters/gauges as values,
        # timings and histograms as distribution summaries (count +
        # p50/p95/p99) — the gateway's SLO tail and a hot path's
        # Timing read the same way in one dump.
        return {
            "counters": {n: c.value for n, c in counters.items()},
            "timings": {n: t.summary() for n, t in timings.items()},
            "gauges": {n: g.value for n, g in gauges.items()},
            "histograms": {n: h.summary() for n, h in histograms.items()},
        }

    def dump_json(self) -> str:
        return json.dumps(self.snapshot(), separators=(",", ":"))


#: Default process-global registry.
metrics = MetricsRegistry()


class MetricsWriter:
    """Append-only JSONL metrics sink for training runs.

    One ``{"ts": ..., "step": ..., **scalars}`` line per emit —
    tail-able during a run, trivially loadable after (pandas/jq); the
    file-based observability tier beneath profiler traces. Flushed per
    line so a SIGKILLed run keeps everything emitted before the kill.
    """

    def __init__(self, path: str):
        import os

        os.makedirs(os.path.dirname(os.path.abspath(path)),
                    exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()

    def emit(self, step: int, **scalars) -> None:
        import math

        rec = {"ts": round(time.time(), 3), "step": int(step)}
        for k, v in scalars.items():
            try:
                f = float(v)
            except (TypeError, ValueError):
                rec[k] = str(v)
                continue
            # json.dumps would emit the invalid-JSON token `NaN` and
            # break jq/strict parsers on exactly the diverging runs
            # where the file matters most — stringify non-finite.
            rec[k] = f if math.isfinite(f) else str(f)
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        with self._lock:
            self._f.write(line)
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


# ------------------------------------------------------------- profiling
# The reference had zap logging only (SURVEY.md §5 "Tracing/profiling:
# Absent"); the TPU build owes JAX profiler traces (XPlane/TensorBoard)
# with annotated steps so Store collective time is attributable.


class trace:
    """Context manager: capture a JAX profiler trace (XPlane) to
    ``logdir`` — view with TensorBoard's profile plugin or xprof.

    >>> with metrics.trace("/tmp/trace"):
    ...     trainer.step(batch)
    """

    def __init__(self, logdir: str):
        self.logdir = logdir

    def __enter__(self):
        jax.profiler.start_trace(self.logdir)
        return self

    def __exit__(self, *exc):
        jax.profiler.stop_trace()
        return False


class _AnnotatedSpan:
    """TraceAnnotation + distributed-trace span entered as one scope —
    profiler timelines and the flight recorder see the same region."""

    __slots__ = ("_ann", "_sp")

    def __init__(self, ann, sp):
        self._ann = ann
        self._sp = sp

    def __enter__(self):
        self._ann.__enter__()
        self._sp.__enter__()
        return self

    def __exit__(self, *exc):
        self._sp.__exit__(*exc)
        return self._ann.__exit__(*exc)


def annotate(name: str, **kwargs):
    """Named region in profiler traces (host + device timeline). Use
    around Store pushes so allreduce time is attributable:

    >>> with metrics.annotate("store.push/grads"):
    ...     store.push_tree("grads", grads)

    When distributed tracing is armed (:mod:`ptype_tpu.trace`), the
    region ALSO opens a span of the same name — store pushes and train
    steps nest inside both the jax profiler trace and the request's
    distributed trace through this one seam. Disabled tracing costs
    one ``enabled()`` check.
    """
    ann = jax.profiler.TraceAnnotation(name, **kwargs)
    if not trace_mod.enabled():
        return ann
    return _AnnotatedSpan(ann, trace_mod.span(name))


def step_annotation(step: int):
    """Mark one training step in the trace (XProf groups by these)."""
    return jax.profiler.StepTraceAnnotation("train", step_num=step)


@dataclass
class StepStats:
    """Rolling per-step throughput tracker for training loops."""

    flops_per_token: float
    n_chips: int
    peak_tflops: float | None = None
    tokens: int = 0
    seconds: float = 0.0
    steps: int = 0
    _t0: float = field(default=0.0, repr=False)

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def step(self, n_tokens: int, n_steps: int = 1) -> None:
        """Fold ``n_tokens`` of COMPLETED work (``n_steps`` train steps)
        into the rolling rates. Callers that dispatch asynchronously must
        only call this at drain boundaries — crediting tokens at dispatch
        time measures queueing rate, not compute (VERDICT r2 weak #5)."""
        now = time.perf_counter()
        self.seconds += now - self._t0
        self._t0 = now
        self.tokens += n_tokens
        self.steps += n_steps

    @property
    def tokens_per_sec(self) -> float:
        return self.tokens / self.seconds if self.seconds else 0.0

    @property
    def tokens_per_sec_per_chip(self) -> float:
        return self.tokens_per_sec / max(self.n_chips, 1)

    @property
    def mfu(self) -> float:
        return mfu(self.tokens_per_sec, self.flops_per_token,
                   self.n_chips, self.peak_tflops)
