"""Checkpoint / resume — sharded, async, Store-aware.

The reference had no application-level checkpointing; durability was
etcd's raft data-dir (SURVEY.md §5 "Checkpoint/resume": Store contents
survive restarts via ``data-dir``). The TPU-native equivalent owed there:
"first-class sharded checkpoint of the Store's parameter space
(Orbax-style async save of jax.Array shards), resume = Join + Store
pull". This module provides both tiers:

- :class:`Checkpointer` — save/restore any jax pytree. Each leaf is
  written **per addressable shard** (device→host copy of exactly this
  process's shards), so an 8B FSDP state never materializes unsharded.
  Restore takes a sharding pytree and ``device_put``s each leaf back
  into placement, and verifies the merged manifest covers every element
  (a partial save fails loudly, never zero-fills). ``async_save``
  snapshots to host synchronously (cheap, device→host DMA) and writes
  files on a background thread — the train loop resumes while bytes hit
  disk.

  **Cross-host**: in multi-controller runs every process calls ``save``
  — each writes only the shards whose ``replica_id`` is 0 (exactly one
  owner per shard box globally) plus its own ``manifest.p<i>.json``
  into the shared step dir; process 0 barriers on all N manifests, then
  commits the marker. ``restore`` merges every per-process manifest and
  can re-place into a different mesh/process set (reshard-on-restore).
- :class:`StoreCheckpoint` — the Store tier: persists a TensorStore
  namespace (values + spec/epoch manifest) into the platform
  ``data_dir``; ``resume()`` re-puts every key with its binding, which
  is exactly "Join + Store pull".

Layout (one directory per step, manifest-first like an orbax step dir):

    <dir>/step_<N>/manifest.json                (single-process saves)
    <dir>/step_<N>/manifest.p<i>.json           (one per process)
    <dir>/step_<N>/<flat-key>[.p<i>].shard<j>.npy
    <dir>/step_<N>/.complete          (commit marker, written last)
"""

from __future__ import annotations

import glob as _glob
import json
import os
import shutil
import threading
import time
import zlib
from typing import Any

import jax
import numpy as np

from ptype_tpu import chaos, logs, retry
from ptype_tpu.errors import CheckpointError, ClusterError

log = logs.get_logger("checkpoint")

_MANIFEST = "manifest.json"
_COMPLETE = ".complete"


def _flat_key(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            part = str(p.key)
        elif hasattr(p, "idx"):
            part = str(p.idx)
        else:
            part = str(p)
        # Keys become filenames: store keys like "params/w" must not
        # introduce directories.
        parts.append(part.replace("/", "%2F"))
    return ".".join(parts) or "_root"


def _proc_info() -> tuple[int, int]:
    """(process_index, process_count) — (0, 1) when jax is absent or
    single-controller."""
    try:
        import jax

        return jax.process_index(), jax.process_count()
    except Exception:  # noqa: BLE001 — control-plane-only processes
        return 0, 1


class Checkpointer:
    """Sharded pytree checkpoints under ``directory``.

    ``barrier_timeout`` bounds how long process 0 waits for the other
    processes' manifests before declaring a multi-controller save
    failed (no commit marker is written — the step stays invisible).
    """

    def __init__(self, directory: str, keep: int = 3,
                 barrier_timeout: float = 120.0):
        self.directory = directory
        self.keep = keep
        self.barrier_timeout = barrier_timeout
        os.makedirs(directory, exist_ok=True)
        self._pending: threading.Thread | None = None

    # ------------------------------------------------------------- save

    def save(self, step: int, tree: Any,
             extras: dict[str, str] | None = None) -> str:
        """Synchronous save; returns the step directory. ``extras`` are
        additional ``{filename: json-text}`` committed WITH the step
        (written before the completion marker). Waits for any pending
        async save first — one writer at a time per Checkpointer.

        Runs as a ``checkpoint.save/<step>`` region through the
        metrics.annotate seam — the goodput ledger's checkpoint leg
        and (when tracing is armed) a span, so a blocking save is
        attributable instead of reading as stall."""
        from ptype_tpu.metrics import annotate

        with annotate(f"checkpoint.save/{step}"):
            self.wait()
            host = self._snapshot(tree)
            return self._write(step, host, extras)

    def async_save(self, step: int, tree: Any) -> None:
        """Snapshot now (device→host), write in the background. At most
        one pending write: a second call waits for the first (backpressure
        rather than unbounded host copies). A failed background write
        (e.g. the multi-controller barrier timeout) re-raises from the
        NEXT ``wait``/``save``/``async_save`` — it must not die silently
        with the daemon thread while training continues uncheckpointed."""
        from ptype_tpu.metrics import annotate

        # Only the BLOCKING leg (drain + device→host snapshot) is the
        # step's checkpoint cost; the background write overlaps compute
        # and must not be attributed against it.
        with annotate(f"checkpoint.snapshot/{step}"):
            self.wait()
            host = self._snapshot(tree)

        def run():
            try:
                self._write(step, host)
            except Exception as e:  # noqa: BLE001 — re-raised on wait()
                self._pending_error = e

        self._pending = threading.Thread(
            target=run, name=f"ckpt-{step}", daemon=True,
        )
        self._pending.start()

    def wait(self) -> None:
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        err = getattr(self, "_pending_error", None)
        if err is not None:
            self._pending_error = None
            raise ClusterError(f"async checkpoint save failed: {err}") \
                from err

    def _snapshot(self, tree: Any) -> list[tuple[str, list, dict]]:
        """Pull this process's OWNED shards to host memory.

        Ownership = ``replica_id == 0``: replication (full or partial)
        puts identical shards on several devices — possibly on several
        hosts — and exactly one replica of each shard box has id 0, so
        the union of every process's snapshot tiles each array exactly
        once with no coordination. Returns
        [(key, [(start, np_array), ...], meta)].
        """
        pid, _ = _proc_info()
        out = []
        leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
        for path, leaf in leaves:
            key = _flat_key(path)
            arr = jax.numpy.asarray(leaf) if np.isscalar(leaf) else leaf
            shards = []
            if isinstance(arr, jax.Array) and arr.addressable_shards:
                # Belt and braces: replica_id==0 already picks one owner
                # per box; the box-dedup guards against exotic shardings
                # that alias boxes within a replica.
                seen: set[tuple] = set()
                for s in arr.addressable_shards:
                    if s.replica_id != 0:
                        continue
                    start = _index_start(s.index, arr.shape)
                    box = (start, tuple(s.data.shape))
                    if box in seen:
                        continue
                    seen.add(box)
                    shards.append((list(start), np.asarray(s.data)))
                dtype = str(arr.dtype)
            else:
                # Host-side leaves are identical everywhere: process 0
                # owns them.
                if pid == 0:
                    shards = [([0] * np.ndim(arr), np.asarray(arr))]
                dtype = str(np.asarray(arr).dtype)
            meta = {"shape": list(np.shape(arr)), "dtype": dtype}
            out.append((key, shards, meta))
        return out

    def _write(self, step: int, host: list,
               extras: dict[str, str] | None = None) -> str:
        pid, nproc = _proc_info()
        if nproc == 1:
            return self._write_single(step, host, extras)
        return self._write_multi(step, host, extras, pid, nproc)

    def _write_single(self, step: int, host: list,
                      extras: dict[str, str] | None) -> str:
        final = self._step_dir(step)
        # Unique per process AND per write: a sync save racing a stale
        # async writer must never share (or rmtree) the other's tmp dir.
        self._seq = getattr(self, "_seq", 0) + 1
        tmp = f"{final}.tmp.{os.getpid()}.{self._seq}"
        os.makedirs(tmp)
        manifest = {"step": step, "leaves": {}}
        for key, shards, meta in host:
            files = []
            for i, (start, data) in enumerate(shards):
                fname = f"{key}.shard{i}.npy"
                files.append(_save_shard(tmp, fname, start, data))
            manifest["leaves"][key] = {**meta, "shards": files}
        with open(os.path.join(tmp, _MANIFEST), "w") as f:
            json.dump(manifest, f)
        for fname, text in (extras or {}).items():
            with open(os.path.join(tmp, fname), "w") as f:
                f.write(text)
        f = chaos.hit("checkpoint.commit", str(step))
        if f is not None and f.action == "crash":
            # Crash between shard write and the commit rename: every
            # shard and the manifest are on disk in the tmp dir, but
            # the step never becomes visible — exactly the state a
            # process death here leaves behind. restore() must fall
            # back to the previous complete step.
            raise CheckpointError(
                f"chaos: crashed before committing step {step} "
                f"(uncommitted shards left in {tmp})")
        with open(os.path.join(tmp, _COMPLETE), "w") as f:
            f.write("ok\n")
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(tmp, final)
        self._gc()
        log.info("checkpoint saved", kv={"step": step, "dir": final})
        chaos.note_ok("checkpoint.save", final)
        return final

    def _write_multi(self, step: int, host: list,
                     extras: dict[str, str] | None,
                     pid: int, nproc: int) -> str:
        """Cross-host save into a SHARED step dir: every process writes
        its owned shards + ``manifest.p<pid>.json`` (each file committed
        via tmp+rename); process 0 barriers on all N manifests and then
        writes the completion marker. A crashed peer ⇒ barrier timeout ⇒
        no marker ⇒ restore ignores the step (never a silent partial)."""
        final = self._step_dir(step)
        os.makedirs(final, exist_ok=True)
        if os.path.exists(os.path.join(final, _COMPLETE)):
            # A COMMITTED checkpoint of this step already exists.
            # Re-writing in place would delete its marker/manifests
            # before the new save commits — a peer crash at the
            # barrier would then have destroyed good committed state.
            # Keep the committed copy; a caller that truly wants a
            # fresh save of the same step deletes the dir first.
            # Guard against SILENT divergence: if what we were asked to
            # save has a different parameter space than what is
            # committed (keys/shapes/dtypes), keeping the old copy
            # would hide a real bug — refuse loudly. Equal-structure
            # re-saves keep the committed copy with a warning (values
            # are not compared; that would need a full read-back).
            mf_path = os.path.join(final, f"manifest.p{pid}.json")
            committed = None
            try:
                with open(mf_path) as f:
                    committed = json.load(f).get("leaves", {})
            except (OSError, ValueError):
                pass  # pre-guard layout or unreadable: keep-and-warn
            if committed is not None:
                mine = json.loads(json.dumps(
                    {key: meta for key, _, meta in host}))
                theirs = {k: {a: b for a, b in v.items()
                              if a != "shards"}
                          for k, v in committed.items()}
                if mine != theirs:
                    raise ClusterError(
                        f"checkpoint step {step} is already committed "
                        f"with a different parameter space — refusing "
                        f"to silently keep the stale copy; delete "
                        f"{final} to re-save this step")
            log.warning(
                "checkpoint step already committed; keeping the "
                "committed copy (tensor values are not compared)",
                kv={"step": step, "dir": final, "process": pid})
            return final
        # Stale-attempt debris (a previous save of this step that timed
        # out or crashed) must never satisfy the barrier: process 0
        # clears EVERY old manifest before writing anything; peers
        # clear their own. A peer's fresh manifest caught in process
        # 0's sweep surfaces as a barrier timeout — loud failure,
        # never a silent merge of two attempts' shards.
        if pid == 0:
            for p in _glob.glob(
                    os.path.join(_glob.escape(final), "manifest*.json")):
                os.unlink(p)
            _rm_f(os.path.join(final, _COMPLETE))
        else:
            _rm_f(os.path.join(final, f"manifest.p{pid}.json"))
        manifest = {"step": step, "process": pid,
                    "num_processes": nproc, "leaves": {}}
        for key, shards, meta in host:
            files = []
            for i, (start, data) in enumerate(shards):
                fname = f"{key}.p{pid}.shard{i}.npy"
                files.append(_save_shard(final, fname, start, data))
            manifest["leaves"][key] = {**meta, "shards": files}
        mf_name = f"manifest.p{pid}.json"
        mf_json = json.dumps(manifest)
        _atomic_write(final, mf_name, mf_json)
        deadline = time.monotonic() + self.barrier_timeout
        if pid == 0:
            # glob.escape: a checkpoint dir containing [ ? * (legal
            # POSIX path chars) must not turn the pattern into a
            # character class that matches nothing — that presents as
            # a spurious barrier timeout only on multi-host runs.
            pat = os.path.join(_glob.escape(final), "manifest.p*.json")
            barrier_bo = retry.Backoff(base=0.05, cap=0.25)
            while len(_glob.glob(pat)) < nproc:
                if time.monotonic() > deadline:
                    # Leave the dir clearly incomplete for the next
                    # attempt: drop our own manifest too.
                    _rm_f(os.path.join(final, "manifest.p0.json"))
                    raise ClusterError(
                        f"checkpoint step {step}: only "
                        f"{len(_glob.glob(pat))}/{nproc} process "
                        f"manifests arrived within {self.barrier_timeout}s"
                        " — not committing"
                    )
                barrier_bo.sleep()
            f = chaos.hit("checkpoint.commit", str(step))
            if f is not None and f.action == "crash":
                # Crash after every shard landed but before the commit
                # marker: the step must stay invisible to restore().
                raise CheckpointError(
                    f"chaos: crashed before committing step {step} "
                    f"(no {_COMPLETE} marker written)")
            for fname, text in (extras or {}).items():
                _atomic_write(final, fname, text)
            _atomic_write(final, _COMPLETE, "ok\n")
            self._gc()
        else:
            # Hold until process 0 commits, RE-ASSERTING our manifest:
            # a peer that outran process 0 has its manifest swept by
            # p0's stale-debris cleanup — rewriting it (idempotent,
            # shards unchanged) turns that race into at most a ~1 s
            # delay instead of a spurious barrier timeout.
            marker = os.path.join(final, _COMPLETE)
            mf_path = os.path.join(final, mf_name)
            commit_bo = retry.Backoff(base=0.2, cap=0.5)
            while not os.path.exists(marker):
                if time.monotonic() > deadline:
                    raise ClusterError(
                        f"checkpoint step {step}: process 0 did not "
                        f"commit within {self.barrier_timeout}s")
                if not os.path.exists(mf_path):
                    _atomic_write(final, mf_name, mf_json)
                commit_bo.sleep()
        log.info("checkpoint shards saved",
                 kv={"step": step, "dir": final, "process": pid})
        chaos.note_ok("checkpoint.save", final)
        return final

    # ---------------------------------------------------------- restore

    def steps(self) -> list[int]:
        """Complete checkpoint steps, ascending."""
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("step_") and os.path.exists(
                os.path.join(self.directory, name, _COMPLETE)
            ):
                try:
                    out.append(int(name[5:]))
                except ValueError:
                    continue
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(self, treedef_like: Any, step: int | None = None,
                shardings: Any | None = None) -> Any:
        """Rebuild the pytree saved at ``step`` (default: latest).

        ``treedef_like`` supplies the tree structure (e.g. an abstract
        state from ``jax.eval_shape`` or a live pytree); ``shardings``,
        when given, is a matching pytree of NamedSharding for device
        placement (the resume-into-mesh path).

        Runs as a ``checkpoint.restore/<step>`` region (annotate seam:
        goodput ledger checkpoint leg + trace span) — a mid-run
        restore blocks the loop and must be attributable."""
        from ptype_tpu.metrics import annotate

        if step is None:
            step = self.latest_step()
            if step is None:
                raise ClusterError(
                    f"no complete checkpoint under {self.directory}"
                )
        with annotate(f"checkpoint.restore/{step}"):
            return self._restore(treedef_like, step, shardings)

    def _restore(self, treedef_like: Any, step: int,
                 shardings: Any | None) -> Any:
        sdir = self._step_dir(step)
        manifest = _merged_manifest(sdir, step)

        leaves, treedef = jax.tree_util.tree_flatten_with_path(treedef_like)
        shard_leaves = (
            jax.tree_util.tree_leaves(shardings) if shardings is not None
            else [None] * len(leaves)
        )
        if len(shard_leaves) != len(leaves):
            raise ClusterError(
                "restore: shardings tree does not match state tree"
            )
        out = []
        for (path, _), sh in zip(leaves, shard_leaves):
            key = _flat_key(path)
            entry = manifest["leaves"].get(key)
            if entry is None:
                raise ClusterError(
                    f"restore: checkpoint {step} has no leaf {key!r}"
                )
            dtype = _resolve_dtype(entry["dtype"])
            full = np.zeros(entry["shape"], dtype=dtype)
            if full.ndim == 0:
                full = _load_shard(sdir, entry["shards"][0], dtype)
            else:
                _check_tiling(key, entry["shards"], entry["shape"])
                for rec in entry["shards"]:
                    data = _load_shard(sdir, rec, dtype)
                    sl = tuple(
                        slice(st, st + sz)
                        for st, sz in zip(rec["start"], data.shape)
                    )
                    full[sl] = data
            arr = jax.device_put(full, sh) if sh is not None else (
                jax.numpy.asarray(full)
            )
            out.append(arr)
        chaos.note_ok("checkpoint.restore", str(step))
        return jax.tree_util.tree_unflatten(treedef, out)

    # ----------------------------------------------------------- intern

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def _gc(self) -> None:
        steps = self.steps()
        for old in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(self._step_dir(old), ignore_errors=True)


def _save_shard(dirpath: str, fname: str, start: list,
                data: np.ndarray) -> dict:
    """Write one shard file (tmp+rename — shared multi-writer dirs must
    never expose partial files) and return its manifest record, which
    carries a crc32 of the logical bytes so restore can tell disk
    corruption from a clean load."""
    raw = data.dtype.kind == "V"
    tmp = os.path.join(dirpath, f".tmp.{fname}.{os.getpid()}")
    with open(tmp, "wb") as f:
        if raw:
            # Extension dtypes (bfloat16 & friends) have no npy cast
            # path: np.save writes them as opaque void and restore
            # cannot assign them back. Persist the raw bytes; the
            # manifest keeps the logical dtype and restore views them
            # back through it.
            payload = data.tobytes()
            crc = zlib.crc32(payload) & 0xFFFFFFFF
            np.save(f, np.frombuffer(payload, np.uint8))
        else:
            # crc32 over the array's own buffer — no tobytes() copy
            # (a multi-GB shard must not transiently double in memory).
            data = np.ascontiguousarray(data)
            crc = zlib.crc32(data) & 0xFFFFFFFF
            np.save(f, data)
    os.replace(tmp, os.path.join(dirpath, fname))
    cf = chaos.hit("checkpoint.shard", fname)
    if cf is not None and cf.action == "corrupt":
        _corrupt_file(os.path.join(dirpath, fname))
    return {"file": fname, "start": start,
            "shape": list(data.shape), "raw": raw, "crc32": crc}


def _corrupt_file(path: str) -> None:
    """Chaos ``checkpoint.shard``/``corrupt``: flip one byte in the
    middle of the file AFTER the manifest checksum was computed — the
    bit-rot restore must catch, never silently load."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1) or b"\x00"
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))


def _atomic_write(dirpath: str, fname: str, text: str) -> None:
    tmp = os.path.join(dirpath, f".tmp.{fname}.{os.getpid()}")
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, os.path.join(dirpath, fname))


def _rm_f(path: str) -> None:
    try:
        os.unlink(path)
    except FileNotFoundError:
        pass


def _index_start(index: tuple, shape: tuple) -> tuple[int, ...]:
    """Shard slice → start offsets (None start = 0)."""
    out = []
    for sl, _ in zip(index, shape):
        out.append(0 if sl.start is None else int(sl.start))
    return tuple(out)


def _merged_manifest(sdir: str, step: int) -> dict:
    """Union of the step's manifests: the single-writer ``manifest.json``
    and/or every per-process ``manifest.p<i>.json``. Leaf shard lists
    concatenate (file names are process-unique); duplicate boxes (e.g. a
    legacy save's replicated copies) keep the first occurrence so the
    tiling check still holds."""
    paths = sorted(
        p for p in _glob.glob(
            os.path.join(_glob.escape(sdir), "manifest*.json")))
    if not paths:
        raise ClusterError(f"restore: step {step} has no manifest")
    per_proc = [p for p in paths
                if os.path.basename(p) != "manifest.json"]
    if per_proc and len(per_proc) != len(paths):
        raise ClusterError(
            f"restore: step {step} mixes a single-writer manifest.json "
            f"with per-process manifests — two save modes' debris")
    merged: dict[str, dict] = {}
    expected_nproc: int | None = None
    for path in paths:
        with open(path) as f:
            m = json.load(f)
        nproc = m.get("num_processes")
        if nproc is not None:
            if expected_nproc is None:
                expected_nproc = nproc
            elif nproc != expected_nproc:
                raise ClusterError(
                    f"restore: step {step} manifests disagree on "
                    f"num_processes ({expected_nproc} vs {nproc}) — "
                    "mixed save attempts")
        for key, entry in m["leaves"].items():
            tgt = merged.setdefault(
                key, {k: v for k, v in entry.items() if k != "shards"})
            tgt.setdefault("shards", []).extend(entry["shards"])
    if expected_nproc is not None and len(per_proc) != expected_nproc:
        raise ClusterError(
            f"restore: step {step} has {len(per_proc)} process manifests "
            f"but the save ran with num_processes={expected_nproc} — "
            "incomplete (uncommitted?) save")
    for entry in merged.values():
        seen: set[tuple] = set()
        uniq = []
        for rec in entry["shards"]:
            box = (tuple(rec["start"]), tuple(rec["shape"]))
            if box in seen:
                continue
            seen.add(box)
            uniq.append(rec)
        entry["shards"] = uniq
    return {"step": step, "leaves": merged}


def _resolve_dtype(name: str) -> np.dtype:
    """Manifest dtype string → dtype, including ml_dtypes extension
    types (bfloat16 etc.) that plain numpy may not resolve by name."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _load_shard(sdir: str, rec: dict, dtype: np.dtype) -> np.ndarray:
    try:
        loaded = np.load(os.path.join(sdir, rec["file"]))
    except (OSError, ValueError) as e:
        # Unreadable/garbled npy (corruption can land in the header):
        # same contract as a checksum mismatch — name the shard.
        raise CheckpointError(
            f"restore: shard {rec['file']!r} is corrupt "
            f"(unreadable: {e})") from e
    want = rec.get("crc32")
    if want is not None:
        # Checksum the loaded buffer in place (raw shards: the uint8
        # payload BEFORE the extension-dtype view, matching what save
        # hashed) — no tobytes() copy of a possibly multi-GB shard.
        got = zlib.crc32(np.ascontiguousarray(loaded)) & 0xFFFFFFFF
        if got != want:
            raise CheckpointError(
                f"restore: shard {rec['file']!r} is corrupt: crc32 "
                f"{got:#010x} != manifest {want:#010x}")
    if rec.get("raw"):
        loaded = loaded.view(dtype).reshape(rec["shape"])
    return np.asarray(loaded)


def _check_tiling(key: str, shards: list[dict], shape: list[int]) -> None:
    """Shards must tile the array exactly: total element count matches
    AND no two boxes overlap (a raw count can be satisfied by overlaps
    masking gaps). O(n²) boxes, n = shard count — tiny."""
    total = int(np.prod(shape)) if shape else 1
    boxes = [(tuple(r["start"]), tuple(r["shape"])) for r in shards]
    covered = sum(int(np.prod(s)) for _, s in boxes)
    overlap = any(
        all(a0 < b0 + bs and b0 < a0 + as_
            for a0, as_, b0, bs in zip(sa, za, sb, zb))
        for i, (sa, za) in enumerate(boxes)
        for sb, zb in boxes[i + 1:]
    )
    if covered != total or overlap:
        raise ClusterError(
            f"restore: leaf {key!r} shards cover {covered} of {total} "
            f"elements{' with overlaps' if overlap else ''} — corrupt "
            "or partial checkpoint (saved from a different process set?)"
        )


class ZeroCheckpoint:
    """Checkpoint tier for ZeRO-1 sharded optimizer state
    (parallel/zero.ZeroState): the per-bucket flat Adam moments are
    jax Arrays sharded over the data axis, so :class:`Checkpointer`
    already writes them as per-replica shard files with a crc32 each
    (the existing per-shard machinery, reused verbatim) — one
    ``bucketNNNNN.{mu,nu}.shard<r>.npy`` per replica shard. The shard
    PLAN rides the step's atomic commit as ``zero_plan.json`` (written
    before ``.complete``), which is what makes restore **reshardable**:
    bucket slots are replica-count-independent, only the tail pads
    depend on N, so a state saved from 8 replicas restores onto 4 (or
    4 onto 8) by strip-pad → re-pad → re-place (ZeroState.
    load_state_tree). A corrupt shard surfaces as
    :class:`~ptype_tpu.errors.CheckpointError` naming the file, same
    contract as every other restore path."""

    def __init__(self, directory: str, keep: int = 3):
        self._ckpt = Checkpointer(directory, keep=keep)

    def latest_step(self) -> int | None:
        return self._ckpt.latest_step()

    def save(self, step: int, zero_state) -> str:
        """Persist the sharded moments + schedule count + plan
        manifest as one committed step dir."""
        return self._ckpt.save(
            step, zero_state.state_tree(),
            extras={"zero_plan.json": json.dumps(
                zero_state.plan.manifest())})

    def restore_into(self, zero_state, step: int | None = None) -> int:
        """Load a saved step INTO an existing ZeroState (whose plan
        defines the restoring replica count), resharding when the
        saved N differs. Returns the restored step. Raises
        CheckpointError on plan mismatch or shard corruption,
        ClusterError when there is nothing to restore."""
        step = step if step is not None else self._ckpt.latest_step()
        if step is None:
            raise ClusterError(
                f"ZeroCheckpoint: no complete step under "
                f"{self._ckpt.directory}")
        sdir = self._ckpt._step_dir(step)
        try:
            with open(os.path.join(sdir, "zero_plan.json")) as f:
                saved_plan = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointError(
                f"ZeroCheckpoint: step {step} has no readable "
                f"zero_plan.json ({e}) — not a sharded-optimizer "
                f"checkpoint") from e
        n_buckets = len(saved_plan.get("buckets", []))
        skeleton = {
            "buckets": {f"{i:05d}": {"mu": 0, "nu": 0}
                        for i in range(n_buckets)},
            "count": 0,
        }
        if getattr(zero_state, "pflat", None) is not None:
            # ZeRO-3: the restoring state holds resident param shards,
            # so pull the saved ones too (state_tree emits them on
            # save; Checkpointer.restore only loads skeleton leaves).
            skeleton["pbuckets"] = {f"{i:05d}": {"p": 0}
                                    for i in range(n_buckets)}
        tree = self._ckpt.restore(skeleton, step=step)
        zero_state.load_state_tree(tree, saved_plan)
        return step


class StoreCheckpoint:
    """Persist / resume a TensorStore namespace (the Store tier).

    Resume is "Join + Store pull" (SURVEY.md §5): a fresh member calls
    ``resume()`` and the parameter space reappears with its bindings —
    the durability role etcd's data-dir played for the reference Store.
    """

    def __init__(self, store, directory: str, keep: int = 3,
                 keys_prefix: str | None = None):
        from ptype_tpu.parallel.tensorstore import TensorStore  # typing

        assert isinstance(store, TensorStore)
        self.store = store
        #: Persist only keys under this prefix (e.g. ``"params/"``) —
        #: a training store also holds transient grads/* whose bytes
        #: match the params'; checkpointing them doubles every save for
        #: state the next step overwrites.
        self.keys_prefix = keys_prefix
        self._ckpt = Checkpointer(directory, keep=keep)

    def latest_step(self) -> int | None:
        """Latest complete step on disk, or None — the is-there-
        anything-to-resume probe (real restore errors then propagate
        from :meth:`resume` instead of being conflated with 'empty')."""
        return self._ckpt.latest_step()

    def save(self, step: int | None = None) -> str:
        from ptype_tpu.parallel.tensorstore import spec_to_json

        keys = self.store.keys()
        if self.keys_prefix:
            keys = [k for k in keys if k.startswith(self.keys_prefix)]
        tree = {k: self.store.get(k) for k in keys}
        step = step if step is not None else max(
            (self.store.epoch(k) for k in keys), default=0
        )
        meta = {
            k: {"spec": spec_to_json(self.store.binding(k).spec),
                "epoch": self.store.epoch(k)}
            for k in keys
        }
        # Meta rides the step's atomic commit (written before .complete),
        # so a crash can never leave a "complete" step resume() rejects.
        return self._ckpt.save(
            step, tree, extras={"store_meta.json": json.dumps(meta)}
        )

    def resume(self, step: int | None = None) -> list[str]:
        """Load the latest (or given) step back into the store; returns
        the restored keys."""
        from ptype_tpu.parallel.tensorstore import spec_from_json

        step = step if step is not None else self._ckpt.latest_step()
        if step is None:
            raise ClusterError("StoreCheckpoint: nothing to resume from")
        sdir = self._ckpt._step_dir(step)
        with open(os.path.join(sdir, "store_meta.json")) as f:
            meta = json.load(f)
        # 0 (not None — None is an empty pytree, not a leaf) marks slots.
        skeleton = {k: 0 for k in meta}
        tree = self._ckpt.restore(skeleton, step=step)
        for key, value in tree.items():
            spec = spec_from_json(meta[key]["spec"])
            self.store.put(key, value, spec=spec,
                           epoch=int(meta[key].get("epoch", 0)))
        return sorted(tree)
