"""Parallelism: device meshes, collectives, and the tensor data plane.

This package is the TPU lowering of the reference's data-movement story
(SURVEY.md §2 parallelism table): the registry becomes the pod's mesh map
(:mod:`mesh`), the Store's push/pull becomes compiled ICI collectives
(:mod:`tensorstore`, :mod:`collectives`), and the strategy modules
(:mod:`sharding`, :mod:`pipeline`, :mod:`ring`) provide DP / FSDP / TP /
PP / SP / EP as first-class components.
"""

from ptype_tpu.parallel.mesh import (  # noqa: F401
    build_mesh,
    local_mesh,
    mesh_from_registry,
    named_sharding,
)
