"""Parallelism: device meshes, collectives, and the tensor data plane.

This package is the TPU lowering of the reference's data-movement story
(SURVEY.md §2 parallelism table): the registry becomes the pod's mesh map
(:mod:`mesh`), the Store's push/pull becomes compiled ICI collectives
(:mod:`tensorstore`, :mod:`collectives`), and the strategy modules
(:mod:`sharding`, :mod:`pipeline`, :mod:`ring`) provide DP / FSDP / TP /
PP / SP / EP as first-class components.
"""

from ptype_tpu.parallel.mesh import (  # noqa: F401
    axis_n,
    build_mesh,
    local_mesh,
    mesh_from_registry,
    named_sharding,
)
from ptype_tpu.parallel.topology import (  # noqa: F401
    DATA_AXIS,
    HIER_AXIS,
    INNER_AXIS,
    OUTER_AXIS,
    LegWire,
    Topology,
)
