"""Topology plane: the 2-D ``(outer, inner)`` device hierarchy.

Everything upstream of this module assumed ONE flat device axis —
``plan_buckets`` packs, one fused shard_map collective per bucket, the
gateway treats every replica as equidistant. Real TPU fleets are
hierarchical: fast ICI inside a pod (the **inner** domain), slow DCN
between pods (the **outer** leg). MLPerf-scale results hinge on
exploiting exactly that split (PAPERS.md: arXiv 1909.09756 —
reduce-scatter inside the fast domain, exchange only ``1/N_inner`` of
the bytes across the slow leg, allgather back out), and a compressed
wire pays hardest on the slow hop (arXiv 2506.17615, EQuARX — quantize
per leg, not per transfer).

:class:`Topology` is the one home for that structure:

- **Mesh construction.** ``topo.mesh()`` builds the 2-D mesh with the
  device grid transposed so that ``Mesh(grid, ("inner", "outer"))``
  places consecutive device ordinals in the same inner domain
  (device ``d`` sits at inner index ``d % n_inner``, outer index
  ``d // n_inner``). The COMPOSITE axis ``("inner", "outer")`` is then
  a drop-in replacement for the old flat ``"data"`` axis: ``P(axis)``
  sharding, ``lax.axis_index(axis)`` linearization, and flat
  collectives over the tuple all behave exactly like the 1-D mesh, so
  ZeRO's :class:`ShardPlan` and the store's bucket space ride
  unchanged.
- **Per-leg wire policy.** :class:`LegWire` resolves the int8+EF wire
  separately for the inner and outer legs — quantize the slow leg
  harder (smaller ``q_block``), keep the fast leg exact or lighter.
- **Analytic cost/byte model.** Per-leg bandwidth/latency numbers feed
  :meth:`flat_allreduce_ms` / :meth:`hier_allreduce_ms` and the
  per-leg byte accounting (:meth:`leg_bytes`). On CPU the model is the
  *emulation*: host meshes have no real ICI/DCN asymmetry, so the
  bench charges measured launch work against the analytic asymmetric
  model deterministically instead of injecting sleeps.
- **Axis-name discipline.** :data:`DATA_AXIS` / :data:`INNER_AXIS` /
  :data:`OUTER_AXIS` are the ONLY sanctioned axis-name literals; lint
  PT023 bars hard-coded ``"data"`` literals outside ``parallel/``.

Env/JSON configuration (``Topology.from_env``): ``PTYPE_TOPOLOGY``
accepts ``"2x4"`` shorthand (outer×inner), an inline JSON object, or
``@/path/to/topology.json``; ``PTYPE_TOPOLOGY_RATIO`` overrides the
emulated inner/outer bandwidth ratio.
"""

from __future__ import annotations

import dataclasses
import json
import os

import numpy as np
from jax.sharding import Mesh

from ptype_tpu.errors import ClusterError

#: The flat data-parallel axis name — the one sanctioned home for the
#: literal (lint PT023 bars hard-coded ``"data"`` outside ``parallel/``).
DATA_AXIS = "data"
#: Fast intra-domain leg (ICI within a pod).
INNER_AXIS = "inner"
#: Slow cross-domain leg (DCN between pods).
OUTER_AXIS = "outer"

#: Composite flat axis over the hierarchical mesh — usable anywhere the
#: 1-D ``"data"`` axis was (``P(...)``, ``lax.axis_index``, collectives).
HIER_AXIS = (INNER_AXIS, OUTER_AXIS)

#: ``PTYPE_TOPOLOGY`` env var consulted by :meth:`Topology.from_env`.
TOPOLOGY_ENV = "PTYPE_TOPOLOGY"
RATIO_ENV = "PTYPE_TOPOLOGY_RATIO"

#: Default emulated bandwidths (GB/s): host-mesh numbers with an 8×
#: inner/outer asymmetry, the shape of ICI-vs-DCN without the scale.
DEFAULT_INNER_GBPS = 16.0
DEFAULT_RATIO = 8.0


@dataclasses.dataclass(frozen=True)
class LegWire:
    """Wire policy for ONE leg of the hierarchy.

    ``compress=None`` means exact (fp32) on this leg; ``"bf16"`` halves
    the payload; ``"int8"`` is the block-scaled quantized wire.
    ``q_block=None`` inherits the caller's default block; a smaller
    block means more scales (finer quantization) — the slow leg
    typically runs a SMALLER block than the fast leg since its bytes
    cost ~an order of magnitude more.
    """

    compress: str | None = None
    q_block: int | None = None

    def __post_init__(self):
        if self.compress not in (None, "bf16", "int8"):
            raise ValueError(
                f"LegWire: compress must be None|'bf16'|'int8', "
                f"got {self.compress!r}")
        if self.q_block is not None and int(self.q_block) < 8:
            raise ValueError(
                f"LegWire: q_block must be >= 8, got {self.q_block}")

    def to_json(self) -> dict:
        return {"compress": self.compress, "q_block": self.q_block}

    @staticmethod
    def from_json(obj: dict | None) -> "LegWire":
        if not obj:
            return LegWire()
        return LegWire(compress=obj.get("compress"),
                       q_block=obj.get("q_block"))


@dataclasses.dataclass(frozen=True)
class Topology:
    """The 2-D device hierarchy: ``n_outer`` domains of ``n_inner``
    devices each, with a per-leg bandwidth/latency model and per-leg
    wire policy. Frozen + hashable so it can key ``lru_cache``'d
    compiled programs alongside the mesh."""

    n_outer: int = 1
    n_inner: int = 1
    #: Per-leg bandwidths in GB/s (the repo's measure_* convention:
    #: bytes / 1e9 / seconds).
    inner_gbps: float = DEFAULT_INNER_GBPS
    outer_gbps: float = DEFAULT_INNER_GBPS / DEFAULT_RATIO
    #: Per-leg one-way latencies in microseconds.
    inner_lat_us: float = 1.0
    outer_lat_us: float = 50.0
    inner_wire: LegWire = dataclasses.field(default_factory=LegWire)
    outer_wire: LegWire = dataclasses.field(default_factory=LegWire)
    #: True when the asymmetry is emulated (host mesh): the cost model
    #: is analytic, not measured — bench records must say so.
    emulated: bool = False

    def __post_init__(self):
        if int(self.n_outer) < 1 or int(self.n_inner) < 1:
            raise ClusterError(
                f"Topology: need n_outer/n_inner >= 1, got "
                f"{self.n_outer}x{self.n_inner}")
        if self.inner_gbps <= 0 or self.outer_gbps <= 0:
            raise ClusterError(
                f"Topology: bandwidths must be > 0, got inner="
                f"{self.inner_gbps} outer={self.outer_gbps}")

    # ------------------------------------------------------- geometry

    @property
    def n(self) -> int:
        """Total device count — the flat axis extent."""
        return int(self.n_outer) * int(self.n_inner)

    @property
    def flat_axis(self) -> tuple:
        """The composite axis standing in for the old flat ``"data"``
        axis on this topology's mesh."""
        return HIER_AXIS

    @property
    def hierarchical(self) -> bool:
        """True when BOTH legs are non-degenerate — i.e. the
        hierarchical decomposition actually changes the wire."""
        return int(self.n_outer) > 1 and int(self.n_inner) > 1

    @property
    def ratio(self) -> float:
        """Inner/outer bandwidth asymmetry — how much more a slow-leg
        byte costs than a fast-leg byte."""
        return float(self.inner_gbps) / float(self.outer_gbps)

    def mesh(self, devices: list | None = None) -> Mesh:
        """Build the 2-D mesh. The grid is ``reshape(n_outer,
        n_inner).T`` so axis names ``("inner", "outer")`` give mesh
        shape ``(n_inner, n_outer)`` with device ``d`` at
        ``(d % n_inner, d // n_inner)`` — domains are CONTIGUOUS
        device-ordinal blocks, matching how a pod's chips enumerate."""
        import jax  # deferred: keep descriptor importable pre-backend

        devs = list(devices if devices is not None else jax.devices())
        if self.n > len(devs):
            raise ClusterError(
                f"Topology: {self.n_outer}x{self.n_inner} needs "
                f"{self.n} devices, have {len(devs)}")
        grid = np.asarray(devs[:self.n], dtype=object).reshape(
            int(self.n_outer), int(self.n_inner)).T
        return Mesh(grid, (INNER_AXIS, OUTER_AXIS))

    def domain_of_device(self, ordinal: int) -> int:
        """Outer-domain index of a flat device ordinal."""
        return int(ordinal) // int(self.n_inner)

    def domain_of_linear(self, lin: int) -> int:
        """Outer-domain index of a composite-axis linear index
        (``lax.axis_index(("inner", "outer"))`` yields
        ``i_inner * n_outer + i_outer``)."""
        return int(lin) % int(self.n_outer)

    def domains(self) -> list:
        """Device ordinals grouped by domain: ``[[0..n_inner-1], ...]``."""
        ni = int(self.n_inner)
        return [list(range(o * ni, (o + 1) * ni))
                for o in range(int(self.n_outer))]

    # ---------------------------------------------------- wire policy

    def leg_wire(self, leg: str) -> LegWire:
        if leg == INNER_AXIS:
            return self.inner_wire
        if leg == OUTER_AXIS:
            return self.outer_wire
        raise ValueError(f"Topology.leg_wire: unknown leg {leg!r}")

    def resolve_leg(self, leg: str, compress, q_block):
        """Resolve the caller's flat wire settings against this leg's
        policy: the leg's explicit setting wins, else inherit the
        caller's. Returns ``(compress, q_block)``."""
        w = self.leg_wire(leg)
        c = w.compress if w.compress is not None else compress
        qb = w.q_block if w.q_block is not None else q_block
        return c, qb

    # --------------------------------------------- analytic cost model

    def _leg_ms(self, nbytes: float, hops: int, leg: str) -> float:
        gbps = (self.inner_gbps if leg == INNER_AXIS
                else self.outer_gbps)
        lat = (self.inner_lat_us if leg == INNER_AXIS
               else self.outer_lat_us)
        return float(nbytes) / (gbps * 1e6) + hops * lat * 1e-3

    def leg_bytes(self, payload: int, kind: str = "allreduce") -> dict:
        """Per-leg wire bytes for ONE device's share of a ``payload``-
        byte bucket. ``kind``: ``"allreduce"`` (hier RS + outer
        exchange + hier AG) or ``"reduce_scatter"`` (no gather leg).
        The FLAT baseline puts its whole ring on the slow leg (a flat
        ring over a 2-D layout must cross domains), so its entry
        charges everything to ``outer``."""
        p = float(payload)
        ni, no, n = int(self.n_inner), int(self.n_outer), self.n
        rs_in = (ni - 1) / ni * p              # inner reduce-scatter
        ag_in = rs_in if kind == "allreduce" else 0.0
        # Outer leg moves only this device's 1/n_inner chunk.
        if kind == "allreduce":
            out = 2.0 * (no - 1) / no * (p / ni)
        else:
            out = (no - 1) / no * (p / ni)
        factor = (2.0 * (n - 1) / n if kind == "allreduce"
                  else (n - 1) / n)
        return {
            "inner": rs_in + ag_in,
            "outer": out,
            "flat_outer": factor * p,
        }

    def flat_allreduce_ms(self, payload: int) -> float:
        """Analytic step cost of the FLAT ring allreduce on this
        topology: every hop of a flat ring over the 2-D layout crosses
        a domain boundary somewhere, so all bytes price at the slow
        leg."""
        n = self.n
        return self._leg_ms(2.0 * (n - 1) / n * payload,
                            2 * (n - 1), OUTER_AXIS)

    def hier_allreduce_ms(self, payload: int) -> float:
        """Analytic step cost of the hierarchical decomposition:
        inner reduce-scatter + outer exchange of ``1/n_inner`` of the
        bytes + inner allgather. Legs serialize (the fused program
        orders them), so costs add."""
        b = self.leg_bytes(payload, "allreduce")
        ni, no = int(self.n_inner), int(self.n_outer)
        rs = self._leg_ms(b["inner"] / 2.0, ni - 1, INNER_AXIS)
        ex = self._leg_ms(b["outer"], 2 * (no - 1), OUTER_AXIS)
        ag = self._leg_ms(b["inner"] / 2.0, ni - 1, INNER_AXIS)
        return rs + ex + ag

    def flat_reduce_scatter_ms(self, payload: int) -> float:
        n = self.n
        return self._leg_ms((n - 1) / n * payload, n - 1, OUTER_AXIS)

    def hier_reduce_scatter_ms(self, payload: int) -> float:
        b = self.leg_bytes(payload, "reduce_scatter")
        ni, no = int(self.n_inner), int(self.n_outer)
        return (self._leg_ms(b["inner"], ni - 1, INNER_AXIS)
                + self._leg_ms(b["outer"], no - 1, OUTER_AXIS))

    # ---------------------------------------------------------- config

    def describe(self) -> dict:
        """Geometry + model summary — rides bench tail records and the
        ``obs topo`` view so numbers are comparable across runs."""
        return {
            "n_outer": int(self.n_outer),
            "n_inner": int(self.n_inner),
            "n": self.n,
            "geometry": f"{int(self.n_outer)}x{int(self.n_inner)}",
            "inner_gbps": float(self.inner_gbps),
            "outer_gbps": float(self.outer_gbps),
            "bandwidth_ratio": self.ratio,
            "emulated": bool(self.emulated),
        }

    def to_json(self) -> dict:
        out = self.describe()
        out.pop("n", None)
        out.pop("geometry", None)
        out.pop("bandwidth_ratio", None)
        out.update({
            "inner_lat_us": float(self.inner_lat_us),
            "outer_lat_us": float(self.outer_lat_us),
            "inner_wire": self.inner_wire.to_json(),
            "outer_wire": self.outer_wire.to_json(),
        })
        return out

    @staticmethod
    def from_json(obj: dict) -> "Topology":
        kw = {}
        for k in ("n_outer", "n_inner"):
            if k in obj:
                kw[k] = int(obj[k])
        for k in ("inner_gbps", "outer_gbps", "inner_lat_us",
                  "outer_lat_us"):
            if k in obj:
                kw[k] = float(obj[k])
        if "emulated" in obj:
            kw["emulated"] = bool(obj["emulated"])
        if "inner_wire" in obj:
            kw["inner_wire"] = LegWire.from_json(obj["inner_wire"])
        if "outer_wire" in obj:
            kw["outer_wire"] = LegWire.from_json(obj["outer_wire"])
        return Topology(**kw)

    @staticmethod
    def emulated_host(n_outer: int, n_inner: int,
                      ratio: float = DEFAULT_RATIO,
                      inner_gbps: float = DEFAULT_INNER_GBPS,
                      **kw) -> "Topology":
        """Host-mesh emulation: the geometry is real (XLA host devices),
        the bandwidth asymmetry is the analytic model — deterministic,
        no sleep injection, so CPU benches are reproducible."""
        return Topology(n_outer=int(n_outer), n_inner=int(n_inner),
                        inner_gbps=float(inner_gbps),
                        outer_gbps=float(inner_gbps) / float(ratio),
                        emulated=True, **kw)

    @staticmethod
    def from_env(env: dict | None = None,
                 n_devices: int | None = None) -> "Topology | None":
        """Read ``PTYPE_TOPOLOGY``: ``"OxI"`` shorthand (``"2x4"`` =
        2 domains × 4 devices), inline JSON, or ``@path`` to a JSON
        file. Returns ``None`` when unset (callers fall back to the
        flat axis). ``PTYPE_TOPOLOGY_RATIO`` overrides the emulated
        bandwidth ratio for the shorthand form."""
        env = os.environ if env is None else env
        raw = (env.get(TOPOLOGY_ENV) or "").strip()
        if not raw:
            return None
        if raw.startswith("@"):
            with open(raw[1:], "r", encoding="utf-8") as f:
                return Topology.from_json(json.load(f))
        if raw.startswith("{"):
            return Topology.from_json(json.loads(raw))
        try:
            o_s, i_s = raw.lower().split("x", 1)
            n_outer, n_inner = int(o_s), int(i_s)
        except ValueError:
            raise ClusterError(
                f"{TOPOLOGY_ENV}={raw!r}: want 'OUTERxINNER' (e.g. "
                "'2x4'), inline JSON, or @/path/to.json") from None
        ratio = float(env.get(RATIO_ENV) or DEFAULT_RATIO)
        return Topology.emulated_host(n_outer, n_inner, ratio=ratio)


def factorizations(n: int) -> list:
    """All ``(outer, inner)`` splits of ``n`` — the test matrix for the
    hierarchical decomposition (for 8: 1x8, 2x4, 4x2, 8x1)."""
    return [(o, n // o) for o in range(1, n + 1) if n % o == 0]


def topology_for(mesh: Mesh) -> "Topology | None":
    """Recover a geometry-only Topology from a hierarchical mesh (both
    hierarchy axes present), else ``None``. Bandwidths are defaults —
    use this for byte accounting, not step-cost claims."""
    names = tuple(mesh.axis_names)
    if INNER_AXIS in names and OUTER_AXIS in names:
        return Topology(n_outer=int(mesh.shape[OUTER_AXIS]),
                        n_inner=int(mesh.shape[INNER_AXIS]))
    return None


def is_hier_axis(axis) -> bool:
    """True when ``axis`` is the composite hierarchy tuple."""
    return (isinstance(axis, tuple) and len(axis) == 2
            and tuple(axis) == HIER_AXIS)
