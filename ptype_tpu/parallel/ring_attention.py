"""Ring attention over the ``seq`` mesh axis — long-context sequence
parallelism.

The reference has no long-context story (SURVEY.md §5: absent — no ML
code); this is the TPU-native build target it mandates: "sequence-axis
sharding with ``ppermute`` ring collectives over ICI (blockwise K/V
rotation)". Each device holds one sequence block of Q, K, V; K/V blocks
rotate around the ICI ring while a flash-style online softmax accumulates
the output, so attention over sequence length S costs O(S/n) memory per
chip and the rotation overlaps with the block matmuls.

Causality is enforced at two levels: whole K/V blocks from later ring
positions are skipped-by-masking, and the diagonal block applies the
usual triangular mask on global positions.

Usage: ``attn_fn = make_ring_attention(mesh)`` → pass to
``transformer.forward``/``make_train_step`` with ``seq_axis=True`` so the
batch's sequence dim is sharded over ``seq``. Degrades to dense attention
when the mesh has no ``seq`` axis (mesh.py axis conventions).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

NEG_INF = jnp.float32(-1e30)


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """GQA: broadcast KV heads across query groups. (B,S,K,Dh)→(B,S,K*r,Dh)."""
    if n_rep == 1:
        return k
    return jnp.repeat(k, n_rep, axis=2)


def _ring_body(q, k, v, *, axis: str, n_blocks: int, causal: bool = True):
    """Per-device ring attention. q,k,v: (B, S_loc, H, Dh) local blocks.

    Online-softmax accumulators (all f32): o (B,S,H,Dh), running max m and
    denominator l (B,H,S). K/V rotate via ppermute; at scan step t this
    device holds the block originating at ring position (idx - t) mod n.
    """
    idx = lax.axis_index(axis)
    B, S, H, Dh = q.shape
    scale = jnp.float32(1.0) / jnp.sqrt(jnp.float32(Dh))

    q_pos = idx * S + jnp.arange(S)  # global query positions
    local_pos = jnp.arange(S)

    o0 = jnp.zeros((B, S, H, Dh), jnp.float32)
    m0 = jnp.full((B, H, S), NEG_INF)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]

    def step(carry, t):
        o, m, l, k, v = carry
        src = (idx - t) % n_blocks  # origin block of the K/V we hold now
        k_pos = src * S + local_pos
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k,
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            # (S_q, S_k) causal mask on GLOBAL positions; whole-block skip
            # for future blocks falls out of the same comparison.
            allowed = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(allowed[None, None], scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])  # (B,H,Q,K) f32
        l = l * correction + jnp.sum(p, axis=-1)
        pv = jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
        o = o * correction.transpose(0, 2, 1)[..., None] + pv

        k = lax.ppermute(k, axis, perm)
        v = lax.ppermute(v, axis, perm)
        return (o, m_new, l, k, v), None

    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(n_blocks)
    )
    o = o / l.transpose(0, 2, 1)[..., None]
    return o.astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis: str = "seq"):
    """Build an ``attn_fn(q, k, v, cfg)`` running ring attention over
    ``axis``. Call sites pass GLOBAL (B, S, H|K, Dh) arrays under jit;
    the shard_map shards S over the ring and B/H over whatever data/model
    axes the mesh has. Falls back to dense attention if the axis is
    absent or trivial."""
    from ptype_tpu.models.transformer import _attention

    n = int(mesh.shape[axis]) if axis in mesh.axis_names else 1
    if n <= 1:
        return _attention

    batch_axes = tuple(
        a for a in ("data", "fsdp") if a in mesh.axis_names
    ) or None
    head_axis = "model" if "model" in mesh.axis_names else None
    spec = P(batch_axes, axis, head_axis, None)

    def attn_fn(q, k, v, cfg):
        H, K = q.shape[2], k.shape[2]
        k = _repeat_kv(k, H // K)
        v = _repeat_kv(v, H // K)
        body = shard_map(
            partial(_ring_body, axis=axis, n_blocks=n, causal=cfg.causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        return body(q, k, v)

    return attn_fn


# ------------------------------------------------------- Ulysses variant


def make_ulysses_attention(mesh: Mesh, axis: str = "seq"):
    """Ulysses-style sequence parallelism: ``all_to_all`` head-scatter.

    Instead of rotating K/V, each device trades its sequence shard for a
    head shard (all_to_all over ``axis``), runs DENSE attention on full
    sequence × (H/n) heads, then trades back. One collective pair per
    attention instead of n−1 ppermutes — wins when heads ≥ ring size and
    ICI all_to_all bandwidth is good (SURVEY.md §5 "Ulysses-style
    head-scatter all_to_all")."""
    from ptype_tpu.models.transformer import _attention

    n = int(mesh.shape[axis]) if axis in mesh.axis_names else 1
    if n <= 1:
        return _attention

    batch_axes = tuple(
        a for a in ("data", "fsdp") if a in mesh.axis_names
    ) or None
    spec = P(batch_axes, axis, None, None)

    def body(q, k, v, *, cfg):
        # (B, S/n, H, Dh) → (B, S, H/n, Dh): scatter heads, gather seq.
        def exch(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        oq, ok, ov = exch(q), exch(k), exch(v)
        o = _attention(oq, ok, ov, cfg)
        # inverse: scatter seq, gather heads
        return lax.all_to_all(o, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    def attn_fn(q, k, v, cfg):
        H, K = q.shape[2], k.shape[2]
        if H % n:
            raise ValueError(
                f"ulysses: n_heads {H} must divide by seq axis size {n}"
            )
        k = _repeat_kv(k, H // K)
        v = _repeat_kv(v, H // K)
        sm = shard_map(
            partial(body, cfg=cfg),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        return sm(q, k, v)

    return attn_fn
