"""Ring attention over the ``seq`` mesh axis — long-context sequence
parallelism.

The reference has no long-context story (SURVEY.md §5: absent — no ML
code); this is the TPU-native build target it mandates: "sequence-axis
sharding with ``ppermute`` ring collectives over ICI (blockwise K/V
rotation)". Each device holds one sequence block of Q, K, V; K/V blocks
rotate around the ICI ring while a flash-style online softmax accumulates
the output, so attention over sequence length S costs O(S/n) memory per
chip and the rotation overlaps with the block matmuls.

Causality is enforced at two levels: whole K/V blocks from later ring
positions are skipped-by-masking, and the diagonal block applies the
usual triangular mask on global positions.

Usage: ``attn_fn = make_ring_attention(mesh)`` → pass to
``transformer.forward``/``make_train_step`` with ``seq_axis=True`` so the
batch's sequence dim is sharded over ``seq``. Degrades to dense attention
when the mesh has no ``seq`` axis (mesh.py axis conventions).
"""

from __future__ import annotations

import math
from functools import partial

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ptype_tpu.compat import shard_map

NEG_INF = jnp.float32(-1e30)


#: Key-chunk width for the fused inner loop. 512 keeps the score
#: transient at (B, K, G, S_loc, 512) f32 — lane-aligned and small —
#: instead of the (S_loc × S_loc) block the round-4 body materialized
#: per ring step (at the S-per-chip scales the seq axis targets, that
#: block IS the memory bill flash attention exists to avoid).
RING_SCORE_CHUNK = 512


def _chunk_width(s_loc: int, chunk: int) -> int:
    """Largest divisor of ``s_loc`` that is <= chunk (power-of-two
    local blocks hit ``chunk`` exactly; odd sizes degrade gracefully
    rather than erroring)."""
    c = min(chunk, s_loc)
    while s_loc % c:
        c -= 1
    return c


def _ring_body(q, k, v, *, axis: str, n_blocks: int, causal: bool = True,
               score_chunk: int = RING_SCORE_CHUNK):
    """Per-device ring attention. q: (B, S_loc, H, Dh); k, v:
    (B, S_loc, K, Dh) — **kv heads stay at K**: query heads are grouped
    (K, G) and contracted against the K kv heads directly, and the ring
    rotates the (G× smaller) K-head blocks. Repeating K/V to H heads
    before sharding (the round-2 lowering) materialized exactly the
    memory GQA + the seq axis exist to avoid (VERDICT r2 weak #4).

    Flash-in-ring (VERDICT r4 weak #6): the inner math is the fused
    blockwise variant carrying the online-softmax state (m, l, acc)
    across BOTH loops — key chunks within a ring step and ring steps
    around the device ring — so no (S_loc × S_loc) score block ever
    materializes; the largest transient is (S_loc × score_chunk).
    Autodiff still differentiates the whole body (nested scans), which
    a Pallas call inside shard_map would not give without a
    hand-written ring-aware VJP.

    Accumulators (all f32): o (B,S,K,G,Dh), running max m and
    denominator l (B,K,G,S). K/V rotate via ppermute; at scan step t
    this device holds the block originating at ring position
    (idx - t) mod n.
    """
    idx = lax.axis_index(axis)
    B, S, H, Dh = q.shape
    K = k.shape[2]
    G = H // K
    qg = q.reshape(B, S, K, G, Dh)
    scale = jnp.float32(1.0) / jnp.sqrt(jnp.float32(Dh))

    q_pos = idx * S + jnp.arange(S)  # global query positions
    C = _chunk_width(S, score_chunk)
    n_chunks = S // C

    o0 = jnp.zeros((B, S, K, G, Dh), jnp.float32)
    m0 = jnp.full((B, K, G, S), NEG_INF)
    l0 = jnp.zeros((B, K, G, S), jnp.float32)
    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]

    def chunk_step(carry, ci, *, k, v, k_pos_base):
        o, m, l = carry
        ks = lax.dynamic_slice_in_dim(k, ci * C, C, axis=1)
        vs = lax.dynamic_slice_in_dim(v, ci * C, C, axis=1)
        scores = jnp.einsum(
            "bqngd,bsnd->bngqs", qg, ks,
            preferred_element_type=jnp.float32,
        ) * scale  # (B, K, G, S_q, C)
        if causal:
            # (S_q, C) causal mask on GLOBAL positions; whole-block
            # skip for future blocks falls out of the same comparison.
            k_pos = k_pos_base + ci * C + jnp.arange(C)
            allowed = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(allowed[None, None, None], scores,
                               NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])  # (B,K,G,Q,C) f32
        l = l * correction + jnp.sum(p, axis=-1)
        pv = jnp.einsum(
            "bngqs,bsnd->bqngd", p.astype(vs.dtype), vs,
            preferred_element_type=jnp.float32,
        )
        o = o * correction.transpose(0, 3, 1, 2)[..., None] + pv
        return (o, m_new, l), None

    def step(carry, t):
        o, m, l, k, v = carry
        src = (idx - t) % n_blocks  # origin block of the K/V we hold now
        (o, m, l), _ = lax.scan(
            partial(chunk_step, k=k, v=v, k_pos_base=src * S),
            (o, m, l), jnp.arange(n_chunks))
        k = lax.ppermute(k, axis, perm)
        v = lax.ppermute(v, axis, perm)
        return (o, m, l, k, v), None

    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(n_blocks)
    )
    o = o / l.transpose(0, 3, 1, 2)[..., None]
    return o.reshape(B, S, H, Dh).astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis: str = "seq",
                        score_chunk: int = RING_SCORE_CHUNK):
    """Build an ``attn_fn(q, k, v, cfg)`` running ring attention over
    ``axis``. Call sites pass GLOBAL (B, S, H|K, Dh) arrays under jit;
    the shard_map shards S over the ring and B/H over whatever data/model
    axes the mesh has. Falls back to dense attention if the axis is
    absent or trivial. ``score_chunk`` bounds the fused inner loop's
    score transient (see _ring_body)."""
    from ptype_tpu.models.transformer import _attention

    n = int(mesh.shape[axis]) if axis in mesh.axis_names else 1
    if n <= 1:
        return _attention

    batch_axes = tuple(
        a for a in ("data", "fsdp") if a in mesh.axis_names
    ) or None
    head_axis = "model" if "model" in mesh.axis_names else None
    spec = P(batch_axes, axis, head_axis, None)

    def attn_fn(q, k, v, cfg):
        # K/V enter at kv_heads (GQA-native — no repeat): the ring
        # rotates blocks G× smaller than the round-2 repeat-first
        # lowering. When a "model" axis shards heads and the kv head
        # count doesn't divide it, pad MINIMALLY (rep = m/gcd(K, m),
        # like Ulysses) so per-device q/kv groups stay aligned — full
        # repeat to H only as the last resort when even the padded
        # count can't group-align with H.
        H, K = q.shape[2], k.shape[2]
        if head_axis and K % int(mesh.shape[head_axis]):
            m = int(mesh.shape[head_axis])
            rep = m // math.gcd(K, m)
            rep = rep if H % (K * rep) == 0 else H // K
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        body = shard_map(
            partial(_ring_body, axis=axis, n_blocks=n,
                    causal=cfg.causal, score_chunk=score_chunk),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        return body(q, k, v)

    return attn_fn


# ------------------------------------------------------- Ulysses variant


def make_ulysses_attention(mesh: Mesh, axis: str = "seq",
                           inner_attn=None):
    """Ulysses-style sequence parallelism: ``all_to_all`` head-scatter.

    Instead of rotating K/V, each device trades its sequence shard for a
    head shard (all_to_all over ``axis``), runs full-sequence attention
    on (H/n) heads, then trades back. One collective pair per attention
    instead of n−1 ppermutes — wins when heads ≥ ring size and ICI
    all_to_all bandwidth is good (SURVEY.md §5 "Ulysses-style
    head-scatter all_to_all").

    ``inner_attn``: the per-device attention after the head scatter —
    ordinary full-sequence attention, so on TPU it defaults to the
    Pallas flash kernel (materializing B·(H/n)·S² f32 scores at the
    sequence lengths the seq axis exists for would be the exact memory
    bill flash avoids); dense XLA elsewhere. The kernel's custom VJP
    differentiates fine under shard_map."""
    from ptype_tpu.models.transformer import _attention

    n = int(mesh.shape[axis]) if axis in mesh.axis_names else 1
    if n <= 1:
        return _attention
    if inner_attn is None:
        from ptype_tpu.models.transformer import default_attn_impl

        if default_attn_impl() == "flash":
            from ptype_tpu.ops.flash_attention import make_flash_attn_fn

            inner_attn = make_flash_attn_fn()
        else:
            inner_attn = _attention

    batch_axes = tuple(
        a for a in ("data", "fsdp") if a in mesh.axis_names
    ) or None
    spec = P(batch_axes, axis, None, None)

    def body(q, k, v, *, cfg):
        # (B, S/n, h, Dh) → (B, S, h/n, Dh): scatter heads, gather seq.
        # K/V are exchanged at their OWN head count (kv_heads for GQA) —
        # repeating them to H heads first would all_to_all G× the bytes
        # and hold H-head tensors per device (VERDICT r2 weak #4). The
        # grouped-einsum dense attention consumes the GQA layout as-is.
        def exch(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        oq, ok, ov = exch(q), exch(k), exch(v)
        o = inner_attn(oq, ok, ov, cfg)
        # inverse: scatter seq, gather heads
        return lax.all_to_all(o, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    def attn_fn(q, k, v, cfg):
        H, K = q.shape[2], k.shape[2]
        if H % n:
            raise ValueError(
                f"ulysses: n_heads {H} must divide by seq axis size {n}"
            )
        if K % n:
            # kv heads don't divide the axis (e.g. K=2, n=4): pad the
            # group structure minimally so the head-scatter stays legal —
            # repeat each kv head just enough that n divides the count.
            rep = n // math.gcd(K, n)
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        sm = shard_map(
            partial(body, cfg=cfg),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        return sm(q, k, v)

    return attn_fn
