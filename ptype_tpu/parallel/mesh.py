"""Device meshes: config axes → ``jax.sharding.Mesh``; registry → mesh map.

The reference's registry mapped service names to node endpoints
(cluster/registry.go:17-26); the north star lowers that map onto TPU device
ordinals so the cluster topology *is* the pod mesh. Two constructors:

- :func:`build_mesh` — from the platform config's ordered ``mesh_axes``
  (``{"data": 2, "model": 4}``) over this process's visible devices.
- :func:`mesh_from_registry` — from the live registry: every node of a
  service advertises its ``device_ordinals``; nodes sorted by process id
  define the global device order. This is the multi-host path, where each
  process sees only its local chips but the mesh must span the pod.

Axis conventions (shared across the framework):
``data`` (DP), ``fsdp`` (param sharding), ``model`` (TP), ``seq``
(SP/ring attention), ``stage`` (pipeline), ``expert`` (EP). Any subset may
appear; strategies look axes up by name and degrade to size-1 when absent.
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ptype_tpu.errors import ClusterError

#: Canonical axis names in canonical order (outer → inner). ICI-heavy axes
#: (model/seq) go innermost so their collectives ride the fastest links.
CANONICAL_AXES = ("stage", "data", "fsdp", "expert", "seq", "model")


def _ordered_axes(axes: dict[str, int]) -> list[tuple[str, int]]:
    """Config order wins; dicts preserve insertion order since py3.7."""
    return [(name, int(size)) for name, size in axes.items()]


def build_mesh(
    axes: dict[str, int],
    axis_names: tuple[str, ...] | None = None,
    devices: list | None = None,
) -> Mesh:
    """Build a Mesh whose axis product covers a prefix of ``devices``.

    ``axes`` is ordered (outer → inner). If ``axis_names`` is given, it
    reorders/subsets the axes. The axis product must not exceed the device
    count; exceeding devices are left out (e.g. an 8-device host running a
    4-device test mesh).
    """
    if not axes:
        raise ClusterError("build_mesh: no mesh axes configured")
    pairs = _ordered_axes(axes)
    if axis_names is not None:
        by_name = dict(pairs)
        missing = [n for n in axis_names if n not in by_name]
        if missing:
            raise ClusterError(f"build_mesh: unknown axes {missing}")
        pairs = [(n, by_name[n]) for n in axis_names]
    names = tuple(n for n, _ in pairs)
    shape = tuple(s for _, s in pairs)
    need = math.prod(shape)
    devs = list(devices if devices is not None else jax.devices())
    if need > len(devs):
        raise ClusterError(
            f"build_mesh: axes {dict(pairs)} need {need} devices, "
            f"have {len(devs)}"
        )
    grid = np.asarray(devs[:need], dtype=object).reshape(shape)
    return Mesh(grid, names)


def local_mesh(**axes: int) -> Mesh:
    """Convenience: ``local_mesh(data=8)`` over this process's devices."""
    return build_mesh(axes)


def mesh_from_registry(registry, service_name: str,
                       axes: dict[str, int]) -> Mesh:
    """Lower a service's registry entries to a Mesh (the mesh-map path).

    Nodes are ordered by ``process_id``; their advertised
    ``device_ordinals`` concatenate into the global device order. Each
    entry must correspond to a device visible to this runtime
    (``jax.devices()`` spans all processes under multi-controller JAX).
    """
    nodes = registry.services().get(service_name, [])
    if not nodes:
        raise ClusterError(
            f"mesh_from_registry: no nodes registered for {service_name!r}"
        )
    nodes = sorted(nodes, key=lambda n: n.process_id)
    ordinals: list[int] = []
    for node in nodes:
        ordinals.extend(node.device_ordinals)
    if not ordinals:
        raise ClusterError(
            f"mesh_from_registry: nodes of {service_name!r} advertise no "
            "device ordinals (control-plane-only processes?)"
        )
    if len(set(ordinals)) != len(ordinals):
        raise ClusterError(
            f"mesh_from_registry: duplicate device ordinals across nodes "
            f"of {service_name!r}: {ordinals}"
        )
    by_id = {d.id: d for d in jax.devices()}
    try:
        devices = [by_id[o] for o in ordinals]
    except KeyError as e:
        raise ClusterError(
            f"mesh_from_registry: registry advertises device {e} not "
            "visible to this runtime"
        ) from e
    return build_mesh(axes, devices=devices)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    """Shorthand: ``named_sharding(mesh, 'data', None)``."""
    return NamedSharding(mesh, PartitionSpec(*spec))


def axis_size(mesh: Mesh, name: str) -> int:
    """Size of a mesh axis, 1 if the axis is absent (strategy degrade)."""
    return int(mesh.shape[name]) if name in mesh.axis_names else 1


def axis_n(mesh: Mesh, axis) -> int:
    """Total extent of ``axis``, which may be a single axis name OR a
    tuple of names (a composite axis, e.g. the topology plane's
    ``("inner", "outer")``). ``mesh.shape`` is a dict keyed by single
    names, so tuple axes need the product — every ``int(mesh.shape[axis])``
    site that can see a hierarchical mesh goes through here."""
    if isinstance(axis, tuple):
        return int(math.prod(int(mesh.shape[a]) for a in axis))
    return int(mesh.shape[axis])


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
