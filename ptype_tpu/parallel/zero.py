"""ZeRO-style sharded optimizer update — reduce-scatter → shard-local
apply → allgather over the TensorStore bucket space.

Store-DP replicated the full optimizer state on every replica, which
caps trainable model size well below what the mesh's memory allows.
Following "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (PAPERS.md, arXiv 2004.13336), this module
implements the full sharding LADDER over the flat bucket space:

- **ZeRO-1** (``_shard_apply_full_fn``): optimizer state sharded,
  grads arrive as full allreduced leaves, each replica slices its
  shard of params AND grads inside the fused apply;
- **ZeRO-2** (``_shard_apply_fn``): grads ride the bucketed
  reduce-scatter and arrive shard-resident — the original path below;
- **ZeRO-3** (``_shard_apply3_fn`` + ``_bucket_gather_fn``): params
  are resident as flat ``P(axis)`` shards too (``ZeroState.pflat``),
  allgathered just-in-time per bucket for the forward; the update is
  purely elementwise with donated buffers.

Live elasticity rides the same math: :meth:`ZeroState.reshard` applies
the ``ZeroCheckpoint.restore_into`` re-pad in memory (strip old tail
pad → re-pad for the survivor count → re-place), atomically, with the
``train.reshard`` chaos seam exercising mid-move faults.

The original ZeRO-2 data path:

- gradients ride a bucketed **reduce-scatter**
  (``collectives.bucketed_reduce_scatter_stream`` /
  ``TensorStore.push_tree_scatter_iter``) — half the allreduce's wire
  bytes, the same block-scaled int8 + error-feedback wire as the
  allreduce paths — leaving each replica ONE contiguous flat shard per
  bucket;
- the optimizer applies **shard-locally**: each replica materializes
  only ``1/N`` of the Adam moments (flat f32 vectors sharded over the
  data axis) and computes only its shard's update — ~N× less optimizer
  memory AND ~N× fewer update FLOPs per replica;
- the updated parameter shards **allgather** back to the replicated
  params, fused into the same per-bucket program as the update (one
  launch per bucket: slice-my-shard → AdamW → all_gather → unpack).

The flat bucket space is the unit of sharding: :class:`ShardPlan`
partitions it (``plan_buckets`` over the sorted leaf keys — the same
planner and therefore the same buckets as the gradient stream), and the
plan's JSON manifest makes sharded checkpoints **reshardable**: bucket
boundaries depend only on leaf order/dtype/``bucket_bytes``, never on
the replica count — only the tail pad does — so a state saved from 8
replicas re-pads onto 4 (checkpoint.ZeroCheckpoint).

The shard-local AdamW mirrors the default recipe
(``trainer.default_optimizer``: clip-by-global-norm → AdamW with
warmup-cosine schedule and a decay mask) element-for-element, with the
hyperparameters read from the one shared
:class:`~ptype_tpu.train.trainer.OptHParams` record. The global-norm
clip — the recipe's one cross-shard coupling — is coordinated through
per-bucket partial square-norms as a device value, exactly like the
overlap trainer's per-bucket apply.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ptype_tpu import chaos
from ptype_tpu.compat import shard_map
from ptype_tpu.errors import CheckpointError, ClusterError
from ptype_tpu.parallel.mesh import axis_n
from ptype_tpu.parallel.collectives import (Bucket, DEFAULT_BUCKET_BYTES,
                                            _slot_offsets, _unpack,
                                            plan_buckets)

#: zero_plan.json schema version.
PLAN_VERSION = 1


@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """Partition of the flat bucket space across ``n`` replicas.

    ``buckets`` come from the SAME planner as the gradient
    reduce-scatter stream (``collectives.plan_buckets`` over leaves in
    store-sorted key order), so slot ``index`` here is a position in
    that sorted order and each bucket's flat ``(elems,)`` payload
    divides into ``n`` contiguous ``elems/n`` shards — replica ``r``
    owns shard ``r`` of every bucket.
    """

    n: int
    bucket_bytes: int
    buckets: tuple  # tuple[Bucket, ...]

    @staticmethod
    def for_leaves(leaves, n: int,
                   bucket_bytes: int = DEFAULT_BUCKET_BYTES
                   ) -> "ShardPlan":
        """Plan over UNSTACKED leaves (params as the trainer holds
        them): each leaf is given the synthetic ``(n, *shape)`` stacked
        form the planner expects, which adds nothing but the leading
        contribution axis — the resulting slots are identical to the
        gradient stream's."""
        fake = [jax.ShapeDtypeStruct((n,) + tuple(np.shape(x)),
                                     jnp.dtype(x.dtype))
                for x in leaves]
        return ShardPlan(n, int(bucket_bytes),
                         tuple(plan_buckets(fake, n, bucket_bytes)))

    def with_n(self, n: int) -> "ShardPlan":
        """The SAME flat space re-padded for ``n`` replicas — the live
        reshard's plan math. Slots (and therefore payloads) are
        untouched; only the tail pads change, exactly as
        ``check_plan_compatible`` permits."""
        n = int(n)
        if n <= 0:
            raise ValueError(f"with_n: need n >= 1, got {n}")
        buckets = tuple(
            dataclasses.replace(b, pad=(-(b.elems - b.pad)) % n)
            for b in self.buckets)
        return ShardPlan(n, self.bucket_bytes, buckets)

    @property
    def n_slots(self) -> int:
        return sum(len(b.slots) for b in self.buckets)

    def shard_elems(self, bucket: Bucket) -> int:
        return bucket.elems // self.n

    def moment_bytes_per_replica(self, itemsize: int = 4) -> int:
        """Adam mu+nu bytes each replica materializes under this plan."""
        return sum(2 * self.shard_elems(b) * itemsize
                   for b in self.buckets)

    def manifest(self) -> dict:
        """JSON-able description — rides the checkpoint commit so a
        restore can validate compatibility and re-pad for a different
        replica count."""
        return {
            "version": PLAN_VERSION,
            "n": self.n,
            "bucket_bytes": self.bucket_bytes,
            "buckets": [
                {"dtype": b.dtype, "pad": b.pad,
                 "slots": [{"index": s.index, "offset": s.offset,
                            "size": s.size, "shape": list(s.shape)}
                           for s in b.slots]}
                for b in self.buckets],
        }


def check_plan_compatible(saved: dict, current: dict) -> None:
    """A saved plan manifest is restorable into the current one iff the
    bucket SLOTS match exactly (same leaves, same offsets, same
    dtypes): slots are replica-count-independent, so only ``n`` and the
    tail pads may differ — that is the reshard case. Anything else
    (different model, different ``bucket_bytes``) is a different flat
    space and must fail loudly, never zero-fill."""
    if saved.get("version") != PLAN_VERSION:
        raise CheckpointError(
            f"zero restore: plan version {saved.get('version')!r} != "
            f"{PLAN_VERSION}")

    def slots_of(m):
        return [(b["dtype"], b["slots"]) for b in m["buckets"]]

    if slots_of(saved) != slots_of(current):
        raise CheckpointError(
            "zero restore: saved shard plan does not match this "
            "trainer's (different parameter space or bucket_bytes) — "
            f"saved {len(saved['buckets'])} buckets / "
            f"{sum(len(b['slots']) for b in saved['buckets'])} slots, "
            f"current {len(current['buckets'])} buckets / "
            f"{sum(len(b['slots']) for b in current['buckets'])} slots")


# ------------------------------------------------- fused shard programs


def _pack_replicated(leaves, pad: int):
    """Flatten + concatenate UNSTACKED leaves and zero-pad — the
    replicated-params analog of ``collectives._pack_flat``."""
    parts = [x.reshape(-1) for x in leaves]
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


@functools.lru_cache(maxsize=512)
def _shard_apply_fn(mesh: Mesh, axis: str, shapes: tuple, dtype: str,
                    pad: int, hp):
    """ONE fused program per bucket: pack params → slice my shard →
    AdamW on the shard only → all_gather updated shards → unpack.

    Args (in order): ``*param_leaves`` (replicated), ``grad_flat``
    (``(elems,)`` sharded ``P(axis)`` — the reduce-scatter output),
    ``mu``/``nu``/``mask`` (flat, sharded ``P(axis)`` — the 1/N
    resident state), ``count`` (int32 scalar), ``scale`` (the
    coordinated global-norm clip scale). Returns
    ``(*new_param_leaves replicated, new_mu, new_nu)``.

    The math mirrors ``optax.chain(clip_by_global_norm, adamw(sched))``
    element-for-element (clip applied as the precomputed ``scale``;
    decay as an elementwise masked add — identical values to optax's
    per-leaf mask for leaf-constant masks), with every hyperparameter
    read from the shared :class:`OptHParams`.
    """
    sched = hp.schedule()
    n = axis_n(mesh, axis)
    in_specs = tuple(P(*(None,) * len(s)) for s in shapes) + (
        P(axis), P(axis), P(axis), P(axis), P(), P())
    out_specs = tuple(P(*(None,) * len(s)) for s in shapes) + (
        P(axis), P(axis))
    offs = _slot_offsets(shapes)

    def f(*args):
        leaves = args[:len(shapes)]
        g, mu, nu, mask, count, scale = args[len(shapes):]
        flat = _pack_replicated(leaves, pad)
        shard = flat.shape[0] // n
        idx = lax.axis_index(axis)
        p_sh = lax.dynamic_slice(flat, (idx * shard,), (shard,))
        p32 = p_sh.astype(jnp.float32)
        g32 = g.astype(jnp.float32) * scale
        mu2 = (1.0 - hp.b1) * g32 + hp.b1 * mu.astype(jnp.float32)
        nu2 = (1.0 - hp.b2) * (g32 * g32) \
            + hp.b2 * nu.astype(jnp.float32)
        cnt1 = (count + 1).astype(jnp.float32)
        mu_hat = mu2 / (1.0 - hp.b1 ** cnt1)
        nu_hat = nu2 / (1.0 - hp.b2 ** cnt1)
        upd = mu_hat / (jnp.sqrt(nu_hat) + hp.eps)
        upd = upd + hp.weight_decay * mask * p32
        new_sh = (p32 - sched(count) * upd).astype(flat.dtype)
        gathered = lax.all_gather(new_sh, axis).reshape(-1)
        out = _unpack(gathered, offs)
        return out + (mu2.astype(mu.dtype), nu2.astype(nu.dtype))

    return jax.jit(shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False))


@functools.lru_cache(maxsize=512)
def _shard_apply_full_fn(mesh: Mesh, axis: str, shapes: tuple,
                         dtype: str, pad: int, hp):
    """ZeRO-1 rung: the grads arrive as FULL reduced leaves (bucketed
    allreduce — ``push_tree_iter``), so the fused program packs BOTH
    params and grads, slices its shard of each, and runs the identical
    shard-local AdamW + all_gather as :func:`_shard_apply_fn`. Same
    optimizer memory as ZeRO-2, but the grads stay replicated — the
    ladder's measurable middle step.

    Args: ``*param_leaves``, ``*grad_leaves`` (both replicated, slot
    order), ``mu``/``nu``/``mask`` (flat ``P(axis)``), ``count``,
    ``scale``. Returns ``(*new_param_leaves, new_mu, new_nu)``.
    """
    sched = hp.schedule()
    n = axis_n(mesh, axis)
    rep = tuple(P(*(None,) * len(s)) for s in shapes)
    in_specs = rep + rep + (P(axis), P(axis), P(axis), P(), P())
    out_specs = rep + (P(axis), P(axis))
    offs = _slot_offsets(shapes)
    L = len(shapes)

    def f(*args):
        leaves = args[:L]
        grads = args[L:2 * L]
        mu, nu, mask, count, scale = args[2 * L:]
        flat = _pack_replicated(leaves, pad)
        gflat = _pack_replicated(grads, pad)
        shard = flat.shape[0] // n
        idx = lax.axis_index(axis)
        p_sh = lax.dynamic_slice(flat, (idx * shard,), (shard,))
        g_sh = lax.dynamic_slice(gflat, (idx * shard,), (shard,))
        p32 = p_sh.astype(jnp.float32)
        g32 = g_sh.astype(jnp.float32) * scale
        mu2 = (1.0 - hp.b1) * g32 + hp.b1 * mu.astype(jnp.float32)
        nu2 = (1.0 - hp.b2) * (g32 * g32) \
            + hp.b2 * nu.astype(jnp.float32)
        cnt1 = (count + 1).astype(jnp.float32)
        mu_hat = mu2 / (1.0 - hp.b1 ** cnt1)
        nu_hat = nu2 / (1.0 - hp.b2 ** cnt1)
        upd = mu_hat / (jnp.sqrt(nu_hat) + hp.eps)
        upd = upd + hp.weight_decay * mask * p32
        new_sh = (p32 - sched(count) * upd).astype(flat.dtype)
        gathered = lax.all_gather(new_sh, axis).reshape(-1)
        out = _unpack(gathered, offs)
        return out + (mu2.astype(mu.dtype), nu2.astype(nu.dtype))

    return jax.jit(shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False))


@functools.lru_cache(maxsize=32)
def _shard_apply3_fn(hp):
    """ZeRO-3 rung: params are RESIDENT as flat ``P(axis)`` shards, the
    reduce-scatter hands each replica exactly its grad shard, so the
    update is purely elementwise — NO collective at all (the forward's
    just-in-time :func:`_bucket_gather_fn` is where the one all_gather
    per bucket lives; progaudit pins this program at zero collectives).

    Donation consumes the old param shard and both moments: the update
    is in-place in the XLA sense, so ZeRO-3's resident footprint never
    doubles mid-step.

    Args: ``p_flat`` (bucket dtype, ``P(axis)``), ``grad_flat``,
    ``mu``/``nu``/``mask`` (f32 flats, ``P(axis)``), ``count``,
    ``scale``. Returns ``(new_p_flat, new_mu, new_nu)``.
    """
    sched = hp.schedule()

    def f(p_flat, g, mu, nu, mask, count, scale):
        p32 = p_flat.astype(jnp.float32)
        g32 = g.astype(jnp.float32) * scale
        mu2 = (1.0 - hp.b1) * g32 + hp.b1 * mu
        nu2 = (1.0 - hp.b2) * (g32 * g32) + hp.b2 * nu
        cnt1 = (count + 1).astype(jnp.float32)
        mu_hat = mu2 / (1.0 - hp.b1 ** cnt1)
        nu_hat = nu2 / (1.0 - hp.b2 ** cnt1)
        upd = mu_hat / (jnp.sqrt(nu_hat) + hp.eps)
        upd = upd + hp.weight_decay * mask * p32
        new_p = (p32 - sched(count) * upd).astype(p_flat.dtype)
        return new_p, mu2, nu2

    return jax.jit(f, donate_argnums=(0, 2, 3))


@functools.lru_cache(maxsize=512)
def _bucket_gather_fn(mesh: Mesh, axis: str, shapes: tuple, dtype: str,
                      pad: int):
    """ZeRO-3's just-in-time param materialization: ONE fused program
    per bucket — all_gather the resident flat shard, unpack to the
    bucket's leaves (replicated). This is the single home for full-tree
    param allgather (lint PT022 bars it from ``train/``); progaudit
    pins it at exactly one ``all_gather`` launch per bucket."""
    offs = _slot_offsets(shapes)
    out_specs = tuple(P(*(None,) * len(s)) for s in shapes)

    def f(flat):
        gathered = lax.all_gather(flat, axis).reshape(-1)
        return _unpack(gathered, offs)

    return jax.jit(shard_map(f, mesh=mesh, in_specs=(P(axis),),
                             out_specs=out_specs, check_vma=False))


#: Partial square-norm of one flat (possibly sharded) buffer — jit
#: handles the sharded input, the cross-shard psum is implied.
_sqnorm = jax.jit(
    lambda flat: jnp.sum(jnp.square(flat.astype(jnp.float32))))


@functools.lru_cache(maxsize=32)
def _scale_fn(clip: float):
    """Global-norm clip scale from stacked per-bucket partial sqnorms —
    the same device-value coordination as the overlap trainer's."""

    def scale_of(sq_stack):
        gnorm = jnp.sqrt(jnp.sum(sq_stack))
        return jnp.where(gnorm < clip, 1.0, clip / gnorm)

    return jax.jit(scale_of)


@functools.lru_cache(maxsize=512)
def _zeros_sharded_fn(mesh: Mesh, axis: str, elems: int, dtype: str):
    """Materialize a flat zeros vector DIRECTLY sharded over ``axis`` —
    shard-local init: no replica ever holds the full moment vector."""
    return jax.jit(
        lambda: jnp.zeros((elems,), jnp.dtype(dtype)),
        out_shardings=NamedSharding(mesh, P(axis)))


class ZeroState:
    """The sharded optimizer state: per-bucket flat Adam moments
    (``mu``/``nu``, f32, sharded ``P(axis)`` — 1/N resident per
    replica), the packed decay-mask vectors, and the shared step
    ``count`` that positions the schedule.
    """

    def __init__(self, plan: ShardPlan, mesh: Mesh, axis: str,
                 hparams, mask_flats: list, mu: list, nu: list,
                 count: int = 0, pflat: list = None):
        self.plan = plan
        self.mesh = mesh
        self.axis = axis
        self.hparams = hparams
        self._masks = mask_flats
        self.mu = mu
        self.nu = nu
        self.count = int(count)
        #: ZeRO-3 only: per-bucket resident param flats (bucket dtype,
        #: sharded ``P(axis)``) — installed by :meth:`scatter_params`,
        #: ``None`` under ZeRO-1/2 where params stay replicated.
        self.pflat = pflat

    @staticmethod
    def create(plan: ShardPlan, mesh: Mesh, axis: str, hparams,
               mask_leaves: list) -> "ZeroState":
        """Init moments sharded from step 0 and pack the per-leaf decay
        mask (True = weight decay applies) into per-bucket flat f32
        vectors. ``mask_leaves`` aligns with the plan's slot order."""
        sh = NamedSharding(mesh, P(axis))
        masks, mu, nu = [], [], []
        for b in plan.buckets:
            vec = np.zeros((b.elems,), np.float32)
            for s in b.slots:
                if bool(mask_leaves[s.index]):
                    vec[s.offset:s.offset + s.size] = 1.0
            masks.append(jax.device_put(vec, sh))
            # Moments are f32 whatever the param dtype — the module's
            # documented contract (and what moment_bytes_per_replica's
            # itemsize=4 accounts): bf16 moments would drop the
            # (1-b2)-scaled nu increments below the mantissa.
            for acc in (mu, nu):
                acc.append(_zeros_sharded_fn(
                    mesh, axis, b.elems, "float32")())
        return ZeroState(plan, mesh, axis, hparams, masks, mu, nu)

    # ----------------------------------------------- ZeRO-3 residency

    def scatter_params(self, param_leaves: list) -> None:
        """Install the params as the RESIDENT sharded layout (ZeRO-3):
        pack each bucket's leaves (``param_leaves`` in plan slot order)
        into the flat space, zero the tail pad, place ``P(axis)``.
        After this the trainer holds no replicated param tree — every
        full materialization goes through :meth:`gather_bucket`."""
        sh = NamedSharding(self.mesh, P(self.axis))
        pflat = []
        for b in self.plan.buckets:
            vec = np.zeros((b.elems,), jnp.dtype(b.dtype))
            for s in b.slots:
                vec[s.offset:s.offset + s.size] = np.asarray(
                    param_leaves[s.index]).reshape(-1)
            pflat.append(jax.device_put(vec, sh))
        self.pflat = pflat

    def gather_bucket(self, bi: int) -> list:
        """Just-in-time full params for bucket ``bi``: one fused
        all_gather + unpack launch; returns replicated leaves in slot
        order. The gathered buffers are TRANSIENT — callers feed them
        to a donating consumer (the grads program) so they die after
        the forward."""
        b = self.plan.buckets[bi]
        fn = _bucket_gather_fn(
            self.mesh, self.axis, tuple(s.shape for s in b.slots),
            b.dtype, b.pad)
        return list(fn(self.pflat[bi]))

    def gather_params(self) -> list:
        """Full param leaves (plan slot order) — the ONE sanctioned
        full-tree materialization under ZeRO-3 (checkpoint export,
        eval, ``params()``)."""
        if self.pflat is None:
            raise ValueError("gather_params: no resident param shards "
                             "(ZeRO-3 only; call scatter_params first)")
        out = [None] * self.plan.n_slots
        for bi, b in enumerate(self.plan.buckets):
            for s, leaf in zip(b.slots, self.gather_bucket(bi)):
                out[s.index] = leaf
        return out

    # --------------------------------------------------------- step ops

    def partial_sqnorm(self, grad_flat):
        return _sqnorm(grad_flat)

    def clip_scale(self, sqnorms: list):
        return _scale_fn(float(self.hparams.clip))(jnp.stack(sqnorms))

    def apply_bucket(self, bi: int, param_leaves: list, grad_flat,
                     scale) -> list:
        """Shard-local AdamW + allgather for bucket ``bi``; updates
        ``mu``/``nu`` in place and returns the new param leaves (slot
        order, replicated). Call :meth:`finish_step` once per step."""
        b = self.plan.buckets[bi]
        fn = _shard_apply_fn(
            self.mesh, self.axis, tuple(s.shape for s in b.slots),
            b.dtype, b.pad, self.hparams)
        outs = fn(*param_leaves, grad_flat, self.mu[bi], self.nu[bi],
                  self._masks[bi], jnp.int32(self.count), scale)
        L = len(b.slots)
        self.mu[bi], self.nu[bi] = outs[L], outs[L + 1]
        return list(outs[:L])

    def apply_bucket_full(self, bi: int, param_leaves: list,
                          grad_leaves: list, scale) -> list:
        """ZeRO-1 apply for bucket ``bi``: full (allreduced) grad
        leaves in, slice-my-shard-of-both inside the fused program;
        otherwise identical contract to :meth:`apply_bucket`."""
        b = self.plan.buckets[bi]
        fn = _shard_apply_full_fn(
            self.mesh, self.axis, tuple(s.shape for s in b.slots),
            b.dtype, b.pad, self.hparams)
        outs = fn(*param_leaves, *grad_leaves, self.mu[bi],
                  self.nu[bi], self._masks[bi], jnp.int32(self.count),
                  scale)
        L = len(b.slots)
        self.mu[bi], self.nu[bi] = outs[L], outs[L + 1]
        return list(outs[:L])

    def apply_bucket3(self, bi: int, grad_flat, scale):
        """ZeRO-3 apply for bucket ``bi``: purely elementwise on the
        resident flats — updates ``pflat``/``mu``/``nu`` in place
        (donated) and returns the new param flat (``P(axis)``) so the
        trainer can commit it to the store. No collective launches."""
        if self.pflat is None:
            raise ValueError("apply_bucket3: no resident param shards "
                             "(ZeRO-3 only; call scatter_params first)")
        fn = _shard_apply3_fn(self.hparams)
        new_p, mu2, nu2 = fn(self.pflat[bi], grad_flat, self.mu[bi],
                             self.nu[bi], self._masks[bi],
                             jnp.int32(self.count), scale)
        self.pflat[bi], self.mu[bi], self.nu[bi] = new_p, mu2, nu2
        return new_p

    def finish_step(self) -> None:
        self.count += 1

    def compiled_cost(self) -> dict:
        """XLA ``cost_analysis`` totals for the per-bucket fused
        shard-apply programs (ISSUE 8 compiled-cost accounting).
        ``cost_analysis`` reports the per-partition SPMD module, so
        the totals are multiplied by the mesh size — the CLUSTER's
        update FLOPs, comparable with the gradient program's
        full-batch count."""
        from ptype_tpu.health.profiling import compiled_cost

        n = axis_n(self.mesh, self.axis)
        flops = nbytes = 0.0
        for b in self.plan.buckets:
            shapes = tuple(s.shape for s in b.slots)
            fn = _shard_apply_fn(self.mesh, self.axis, shapes,
                                 b.dtype, b.pad, self.hparams)
            dt = jnp.dtype(b.dtype)
            leaves = [jax.ShapeDtypeStruct(s, dt) for s in shapes]
            vec = jax.ShapeDtypeStruct((b.elems,), jnp.float32)
            c = compiled_cost(
                fn, *leaves, jax.ShapeDtypeStruct((b.elems,), dt),
                vec, vec, vec, jax.ShapeDtypeStruct((), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.float32))
            flops += c["flops"] * n
            nbytes += c["bytes_accessed"] * n
        return {"flops": flops, "bytes_accessed": nbytes,
                "n_buckets": len(self.plan.buckets)}

    # ------------------------------------------------------- accounting

    def moment_bytes_per_replica(self) -> int:
        """Measured, not planned: the actual per-replica bytes of the
        resident moment shards."""
        total = 0
        for arr in list(self.mu) + list(self.nu):
            shards = getattr(arr, "addressable_shards", None)
            total += (shards[0].data.nbytes if shards
                      else arr.nbytes)
        return total

    def param_bytes_per_replica(self) -> int:
        """Measured per-replica bytes of the resident ZeRO-3 param
        shards (0 when params are replicated — ZeRO-1/2)."""
        if self.pflat is None:
            return 0
        total = 0
        for arr in self.pflat:
            shards = getattr(arr, "addressable_shards", None)
            total += (shards[0].data.nbytes if shards
                      else arr.nbytes)
        return total

    # ------------------------------------------------- live resharding

    def reshard(self, mesh: Mesh, axis: str = None) -> None:
        """Re-place the WHOLE resident state (moments, masks, and the
        ZeRO-3 param flats if present) onto ``mesh`` — the
        ``ZeroCheckpoint.restore_into`` reshard math applied in memory:
        gather each flat to host, strip the old tail pad, zero-pad for
        the survivor count, place ``P(axis)`` on the new mesh. Values
        in ``[:total]`` are byte-copied, so moments are bit-preserved.

        ATOMIC: everything is staged into locals and swapped in only
        after the last bucket lands. A fault mid-loop (the
        ``train.reshard`` chaos seam, a placement error) leaves the old
        plan/mesh/arrays fully intact, so the caller can retry against
        the same state.
        """
        axis = axis or self.axis
        new_n = axis_n(mesh, axis)
        new_plan = self.plan.with_n(new_n)
        sh = NamedSharding(mesh, P(axis))
        groups = [("mu", self.mu), ("nu", self.nu),
                  ("mask", self._masks)]
        if self.pflat is not None:
            groups.append(("p", self.pflat))
        staged = {name: [] for name, _ in groups}
        for i, (old_b, new_b) in enumerate(zip(self.plan.buckets,
                                               new_plan.buckets)):
            f = chaos.hit("train.reshard", f"bucket{i:05d}")
            if f is not None:
                if f.action == "drop":
                    raise ClusterError(
                        f"chaos: reshard dropped at bucket {i} "
                        f"(plan unchanged; retry)")
                f.sleep()  # delay / wedge: stall this bucket's move
            total = old_b.elems - old_b.pad
            for name, acc in groups:
                full = np.asarray(acc[i])
                out = np.zeros((new_b.elems,), full.dtype)
                out[:total] = full[:total]
                staged[name].append(jax.device_put(out, sh))
            # Per-bucket recovery beacon, mirroring the per-bucket
            # hit: a delayed/wedged bucket pairs on its own landing.
            chaos.note_ok("train.reshard", f"bucket{i:05d}")
        # -- atomic swap: nothing above mutated self.
        self.plan = new_plan
        self.mesh = mesh
        self.axis = axis
        self.mu = staged["mu"]
        self.nu = staged["nu"]
        self._masks = staged["mask"]
        if self.pflat is not None:
            self.pflat = staged["p"]
        chaos.note_ok("train.reshard", f"n={new_n}")

    # ------------------------------------------------------- checkpoint

    def state_tree(self) -> dict:
        """The checkpointable pytree: per-bucket sharded moments (the
        Checkpointer writes one crc32'd shard file per replica shard)
        plus the schedule count. Masks are derived state — rebuilt from
        the params at init, never persisted."""
        tree = {
            "buckets": {f"{i:05d}": {"mu": self.mu[i], "nu": self.nu[i]}
                        for i in range(len(self.plan.buckets))},
            "count": jnp.int32(self.count),
        }
        if self.pflat is not None:
            tree["pbuckets"] = {f"{i:05d}": {"p": self.pflat[i]}
                                for i in range(len(self.plan.buckets))}
        return tree

    def load_state_tree(self, tree: dict, saved_plan: dict) -> None:
        """Install restored moments, RE-SHARDING when the saved replica
        count differs: slots are n-independent, so resharding is
        strip-the-old-tail-pad → re-pad for this plan → place
        ``P(axis)`` on this mesh. ``tree`` holds full host arrays (the
        Checkpointer merged the per-replica shards already)."""
        check_plan_compatible(saved_plan, self.plan.manifest())
        saved_buckets = saved_plan["buckets"]
        sh = NamedSharding(self.mesh, P(self.axis))
        for i, b in enumerate(self.plan.buckets):
            total = b.elems - b.pad
            old_pad = int(saved_buckets[i]["pad"])
            for name, acc in (("mu", self.mu), ("nu", self.nu)):
                full = np.asarray(tree["buckets"][f"{i:05d}"][name])
                if full.shape != (total + old_pad,):
                    raise CheckpointError(
                        f"zero restore: bucket {i} {name} has "
                        f"{full.shape} elements, manifest says "
                        f"{total + old_pad}")
                out = np.zeros((b.elems,), np.float32)
                out[:total] = full[:total]
                acc[i] = jax.device_put(out, sh)
            if self.pflat is not None and "pbuckets" in tree:
                full = np.asarray(tree["pbuckets"][f"{i:05d}"]["p"])
                if full.shape != (total + old_pad,):
                    raise CheckpointError(
                        f"zero restore: bucket {i} params have "
                        f"{full.shape} elements, manifest says "
                        f"{total + old_pad}")
                out = np.zeros((b.elems,), jnp.dtype(b.dtype))
                out[:total] = full[:total]
                self.pflat[i] = jax.device_put(out, sh)
        # reshape(-1)[0]: the Checkpointer round-trips 0-d scalars as
        # shape (1,) — accept either form.
        self.count = int(np.asarray(tree["count"]).reshape(-1)[0])
