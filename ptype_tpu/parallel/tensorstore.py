"""TensorStore — the Store's tensor tier: push/pull as ICI collectives.

The reference Store was a namespaced KV over raft (cluster/store.go:38-74):
``Put`` replicated a value to every member, ``Get`` read it linearizably.
The north star (BASELINE.json) lowers exactly that contract onto the mesh:

- ``push(key, contributions)``  → allreduce (``psum``/``pmean``) — every
  device ends up with the same reduced tensor, like a raft-replicated Put.
- ``push_scatter(key, ...)``    → reduce-scatter — each device keeps one
  shard (half the ICI bytes; the FSDP/ZeRO-style reduction).
- ``pull(key)``                 → the stored array, or an allgathered
  replicated view (``gather=True``), like a linearizable Get.

Values live device-resident under a per-key **binding** (a PartitionSpec),
so a pull never round-trips through the host. Ordering, which the
reference got free from raft linearizability, is provided by an explicit
**epoch**: every push bumps the key's epoch, and the optional metadata
KVStore carries ``{shape, dtype, spec, epoch}`` manifests so any member
(or a checkpointer) can discover the parameter space — the control-plane/
data-plane split mandated by SURVEY.md §7 stage 6.

Compression hooks (EQuARX pattern, PAPERS.md):

- ``compress="bf16"`` casts contributions to bfloat16 for the wire and
  restores dtype after the reduce — halves ICI bytes at <1 ulp-bf16 cost.
- ``compress="int8"`` runs push through the two-phase int8-quantized
  allreduce (``collectives.quantized_all_reduce``: all_to_all
  reduce-scatter leg + all_gather leg, both carrying int8 payloads with
  f32 absmax scales) — ≈4× fewer ICI bytes; lossy, meant for gradients.
  Leaves too small to chunk over the axis (scalars, short vectors) ride
  the exact allreduce instead.
"""

from __future__ import annotations

import json
import threading
import time as _time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ptype_tpu import chaos, logs
from ptype_tpu.errors import ClusterError, CoordinationError, NoKeyError
from ptype_tpu.parallel import collectives
from ptype_tpu.parallel.mesh import axis_n
from ptype_tpu.parallel.topology import Topology
from ptype_tpu.store import KVStore

log = logs.get_logger("tensorstore")

TENSOR_PREFIX = "tensors"


def _store_fault(site: str, key: str) -> None:
    """Apply an armed store fault: ``delay`` (a straggler bucket —
    the collective completes late) sleeps; ``timeout`` raises before
    any state changes, so the caller's retry re-runs a clean push."""
    f = chaos.hit(site, key)
    if f is None:
        return
    if f.action == "delay":
        f.sleep()
    elif f.action == "timeout":
        raise ClusterError(f"chaos: {site} timed out for {key!r}")


def spec_to_json(spec: P) -> str:
    return json.dumps([list(p) if isinstance(p, tuple) else p for p in spec])


def spec_from_json(raw: str) -> P:
    return P(*[tuple(p) if isinstance(p, list) else p for p in json.loads(raw)])


@dataclass
class Binding:
    """Per-key placement + reduction policy."""

    spec: P = P()
    reduce_op: str = "mean"


@dataclass
class _Entry:
    value: jax.Array
    epoch: int = 0
    binding: Binding = field(default_factory=Binding)
    #: Store-wide monotonic write stamp — lets a caller that itself
    #: wrote the key detect EXTERNAL mutations without re-pulling
    #: (epoch can't: put() resets it, so two writers look identical).
    seq: int = 0


@dataclass
class BucketPush:
    """One dispatched bucket of a streamed :meth:`TensorStore.
    push_tree_stream`: the committed per-key views (async jax arrays),
    plus a :meth:`wait` that blocks on them inside a
    ``store.push_wait`` region — so the time a consumer actually
    spends waiting on this bucket's collective lands in the goodput
    ledger's collective leg, not in untracked compute."""

    prefix: str
    keys: list
    values: list

    def items(self):
        return zip(self.keys, self.values)

    def wait(self) -> "BucketPush":
        from ptype_tpu.metrics import annotate

        with annotate(f"store.push_wait/{self.prefix}"):
            for v in self.values:
                v.block_until_ready()
        return self


@dataclass
class ShardPush:
    """One dispatched bucket of :meth:`TensorStore.push_tree_scatter_
    iter`: the committed flat reduction, sharded ``P(axis)`` — each
    replica holds its contiguous ``elems/n`` shard (the ZeRO resident
    form). ``keys`` are the leaf keys packed into the bucket, in slot
    order; :meth:`wait` blocks inside a ``store.push_wait`` region so
    consumer wait time lands in the goodput ledger's collective leg."""

    prefix: str
    index: int
    key: str
    bucket: object          # collectives.Bucket
    keys: list
    flat: jax.Array

    def wait(self) -> "ShardPush":
        from ptype_tpu.metrics import annotate

        with annotate(f"store.push_wait/{self.prefix}"):
            self.flat.block_until_ready()
        return self


class TensorStore:
    """Device-resident tensor KV over a mesh (the Store push/pull lowering)."""

    def __init__(self, mesh: Mesh, axis: str = "data",
                 kv: KVStore | None = None, namespace: str = "params",
                 compress: str | None = None,
                 wire: collectives.WireConfig | None = None,
                 topology: Topology | None = None):
        if (wire is not None and compress is not None
                and compress != wire.compress):
            raise ValueError(
                f"TensorStore: conflicting compress={compress!r} and "
                f"wire.compress={wire.compress!r} — pass one")
        self.wire = (wire if wire is not None
                     else collectives.WireConfig(compress=compress))
        #: Hierarchical topology: every tree push rides the per-leg
        #: decomposition (collectives._hier_bucket_*) over the
        #: composite ("inner", "outer") axis. The default axis follows
        #: the topology so call sites (ZeRO trainers, store-DP) stay
        #: unchanged.
        self.topology = topology
        if topology is not None and axis == "data":
            axis = topology.flat_axis
        self.mesh = mesh
        self.axis = axis
        self.namespace = namespace
        self.compress = self.wire.compress
        self._kv = kv
        self._entries: dict[str, _Entry] = {}
        self._bindings: dict[str, Binding] = {}
        self._lock = threading.RLock()
        self._manifest_failed: set[str] = set()
        #: Per-key error-feedback residuals (stacked layout) for the
        #: int8 wire — each pushing process carries its own local
        #: quantization error into its next contribution.
        self._residuals: dict[str, jax.Array] = {}
        #: Per-push-site OUTER-leg residuals for the hierarchical
        #: int8 wire: site → {bucket index → flat f32 sharded
        #: ``P(flat_axis)``}. Outer-leg quantization error lives at
        #: bucket granularity (the cross-domain chunk boundaries cut
        #: across leaf slots, so a per-leaf keying cannot represent
        #: it); the collectives stream mutates the popped dict in
        #: place and this store carries it across steps under the
        #: same pop/store-back ownership as the per-leaf residuals.
        self._outer_residuals: dict[str, dict[int, jax.Array]] = {}
        self._seq = 0
        #: prefix → highest write stamp under it (every "/"-ancestor
        #: of each written key) — tree_seq in O(1) instead of an
        #: all-entries scan under the lock on every cache check.
        self._prefix_seq: dict[str, int] = {}

    # ---------------------------------------------------------- bindings

    def bind(self, key: str, spec: P = P(), reduce_op: str = "mean") -> None:
        """Declare a key's sharding + reduction before first use.

        Unbound keys default to replicated placement and mean reduction —
        the closest analog of the reference's replicate-everywhere Put.
        """
        with self._lock:
            self._bindings[key] = Binding(spec, reduce_op)
            if key in self._entries:
                self._entries[key].binding = self._bindings[key]

    def binding(self, key: str) -> Binding:
        with self._lock:
            return self._bindings.get(key, Binding())

    # ------------------------------------------------------------- basic

    def put(self, key: str, value, spec: P | None = None,
            epoch: int = 0) -> jax.Array:
        """Place a value under the key's binding; no collective, epoch
        reset to ``epoch`` (default 0 — a checkpoint resume passes the
        saved epoch so versions never go backwards). The
        initial-parameters path (ref Put store.go:56-62). Passing
        ``spec`` records it as the key's binding, same as bind()."""
        if spec is None:
            b = self.binding(key)
        else:
            b = Binding(spec, self.binding(key).reduce_op)
        arr = jax.device_put(jnp.asarray(value), NamedSharding(self.mesh, b.spec))
        with self._lock:
            if spec is not None:
                self._bindings[key] = b
            self._entries[key] = _Entry(arr, epoch, b,
                                        self._stamp_locked(key))
        self._publish(key)
        return arr

    def get(self, key: str) -> jax.Array:
        """The stored array in its bound sharding (ref Get store.go:38-53)."""
        with self._lock:
            entry = self._entries.get(key)
        if entry is None:
            raise NoKeyError(key)
        return entry.value

    def pull(self, key: str, gather: bool = False) -> jax.Array:
        """Get; with ``gather=True``, return a fully-replicated view
        (allgather lowering of a linearizable read)."""
        from ptype_tpu.metrics import annotate

        with annotate(f"store.pull/{key}"):
            _store_fault("store.pull", key)
            value = self.get(key)
            if gather:
                value = jax.device_put(value,
                                       NamedSharding(self.mesh, P()))
            chaos.note_ok("store.pull", key)
            return value

    def delete(self, key: str) -> None:
        with self._lock:
            if key not in self._entries:
                raise NoKeyError(key)
            del self._entries[key]
            self._stamp_locked(key)  # a deletion is a mutation: cached
            #                   readers must notice and re-pull
        if self._kv is not None:
            try:
                self._kv.delete(self._manifest_key(key))
            except NoKeyError:
                pass

    def keys(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    def epoch(self, key: str) -> int:
        with self._lock:
            entry = self._entries.get(key)
        if entry is None:
            raise NoKeyError(key)
        return entry.epoch

    def tree_seq(self, prefix: str) -> int:
        """Highest store-wide write stamp under ``prefix/`` (0 when
        never written; deletions bump it too — they are mutations). A
        caller that recorded this after its own put_tree can cheaply
        detect whether ANY other writer has since touched the
        namespace — the re-pull guard train/store_dp.py uses instead
        of a full get_tree every step."""
        with self._lock:
            return self._prefix_seq.get(prefix, 0)

    def _stamp_locked(self, key: str) -> int:
        """Bump the store write stamp and index it under every
        "/"-ancestor of ``key``; callers hold the lock."""
        self._seq += 1
        parts = key.split("/")
        for i in range(1, len(parts)):
            self._prefix_seq["/".join(parts[:i])] = self._seq
        return self._seq

    # ------------------------------------------------------------- push

    def push(self, key: str, stacked, op: str | None = None) -> jax.Array:
        """Reduce per-worker contributions into the key — the allreduce
        lowering of Store.Put (north star). ``stacked``'s leading dim is
        the contribution axis (== mesh axis size); the reduced tensor is
        stored under the key's binding and returned.

        Rides the same single-bucket fused program as the tree pushes,
        so the wire policy is uniform across every push path: the int8
        wire is block-scaled, the bucket pad removes the per-leaf
        ``rest[0] % n`` eligibility lottery (the size floor
        ``int8_min_bytes`` still routes small leaves exact), and an
        armed error-feedback residual is carried per key here too —
        EF must not silently vanish because a caller used the per-key
        API instead of push_tree."""
        from ptype_tpu.metrics import annotate

        b = self.binding(key)
        op = op or b.reduce_op
        stacked = jnp.asarray(stacked)
        with annotate(f"store.push/{key}"):
            # Fault seam INSIDE the region: a chaos straggler delay
            # must be attributed to the collective leg of the goodput
            # breakdown, exactly like a real slow allreduce.
            _store_fault("store.push", key)
            items = [(key, stacked)]
            res = self._group_residuals(items)
            ores = self._pop_outer(key)
            try:
                outs = collectives.bucketed_all_reduce(
                    [stacked], self.mesh, self.axis, op, residuals=res,
                    outer_residuals=ores, **self._wire_kwargs(None))
            except BaseException:
                self._restore_residuals(items, res)
                self._restore_outer(key, ores)
                raise
            if res is not None:
                outs, new_res = outs
                self._store_residuals(items, new_res)
            self._store_outer(key, ores)
            reduced = outs[0]
        return self._commit_reduced(key, reduced)

    def push_scatter(self, key: str, stacked, op: str | None = None) -> jax.Array:
        """Reduce-scatter variant: each device keeps one shard of the
        reduced tensor (binding forced to shard dim 0 over the push axis).
        Pull with ``gather=True`` to reassemble — together they form the
        bandwidth-optimal allreduce decomposition."""
        _store_fault("store.push", key)
        b = Binding(P(self.axis), op or self.binding(key).reduce_op)
        stacked = jnp.asarray(stacked)
        n = axis_n(self.mesh, self.axis)
        if (self.compress == "int8"
                and collectives.quantized_all_reduce_eligible(
                    stacked.shape, n, b.reduce_op)):
            reduced = collectives.quantized_reduce_scatter(
                stacked, self.mesh, self.axis, b.reduce_op,
                q_block=self.wire.q_block)
        else:
            # int8-ineligible leaves ride the exact allreduce — the
            # caller opted into int8 loss, not bf16 loss.
            wire = (stacked.astype(jnp.bfloat16)
                    if self.compress == "bf16" else stacked)
            reduced = collectives.reduce_scatter(
                wire, self.mesh, self.axis, b.reduce_op)
        if self.compress:
            reduced = reduced.astype(stacked.dtype)
        return self._commit(key, reduced, b)

    def _commit(self, key: str, value: jax.Array, b: Binding) -> jax.Array:
        with self._lock:
            prev = self._entries.get(key)
            epoch = (prev.epoch + 1) if prev else 1
            self._entries[key] = _Entry(value, epoch, b,
                                        self._stamp_locked(key))
        self._publish(key)
        chaos.note_ok("store.push", key)
        return value

    def commit_sharded(self, key: str, flat: jax.Array) -> jax.Array:
        """Commit an ALREADY-PLACED ``P(axis)`` flat under ``key`` with
        push epoch semantics (epoch bumps, manifest publishes) — the
        ZeRO-3 trainer's per-step resident-param commit. No collective,
        no re-placement: the caller's fused apply produced the flat in
        its final sharding already."""
        return self._commit(key, flat, Binding(P(self.axis)))

    def reshard(self, mesh: Mesh, axis: str | None = None) -> None:
        """Re-home the store on a new (survivor) mesh — the live
        elastic reshard's store leg. Replicated entries are re-placed
        onto the new mesh with their epochs preserved; axis-SHARDED
        entries (scatter-path grad flats, ZeRO-3 param flats) are
        dropped, because their payloads are padded for the OLD replica
        count — their owner re-commits them in the new layout (the
        trainer re-pads via ``ZeroState.reshard``). Error-feedback
        residuals reset for the same reason: they are laid out per the
        old contribution count."""
        axis = axis or self.axis
        with self._lock:
            entries = list(self._entries.items())
        for key, entry in entries:
            if entry.binding.spec == P():
                arr = jax.device_put(np.asarray(entry.value),
                                     NamedSharding(mesh, P()))
                with self._lock:
                    cur = self._entries.get(key)
                    if cur is entry:
                        self._entries[key] = _Entry(
                            arr, entry.epoch, entry.binding,
                            self._stamp_locked(key))
            else:
                with self._lock:
                    self._entries.pop(key, None)
                    self._stamp_locked(key)
        with self._lock:
            self._residuals.clear()
            self._outer_residuals.clear()
        # mesh/axis are rebind-on-reshard like __init__'s bare writes:
        # the trainer quiesces pushes across a reshard (the step that
        # raised never ran), so no concurrent reader sees the old mesh.
        self.mesh = mesh
        self.axis = axis

    # -------------------------------------------------------------- tree

    def put_tree(self, prefix: str, tree) -> int:
        """Place every leaf under its path-derived key (no collective).

        All host→device transfers dispatch through ONE batched
        device_put instead of a per-leaf loop, then each key commits
        with the same epoch-0/binding/manifest semantics as
        :meth:`put`. Returns the highest write stamp THIS call
        assigned — the stamp a caller records to detect external
        writers via :meth:`tree_seq` (re-reading the global max after
        the fact would absorb a concurrent writer's stamp and hide
        their write)."""
        pairs = _flatten(prefix, tree)
        bindings = [self.binding(key) for key, _ in pairs]
        arrs = jax.device_put(
            [jnp.asarray(leaf) for _, leaf in pairs],
            [NamedSharding(self.mesh, b.spec) for b in bindings])
        with self._lock:
            for (key, _), b, arr in zip(pairs, bindings, arrs):
                self._entries[key] = _Entry(arr, 0, b, self._stamp_locked(key))
            assigned = self._seq
        for key, _ in pairs:
            self._publish(key)
        return assigned

    def push_tree(self, prefix: str, stacked_tree, op: str | None = None,
                  *, bucketed: bool = True,
                  bucket_bytes: int | None = None) -> dict[str, jax.Array]:
        """Push every leaf of a pytree of stacked contributions.

        Bucketed (the default): leaves are grouped by reduce op, packed
        into large same-dtype flat buckets, and reduced with ONE fused
        collective per bucket (``collectives.bucketed_all_reduce``) —
        the whole optimus-125M tree costs ceil(bytes/bucket) launches
        per dtype group instead of one per leaf, and every bucket is in
        flight before the first result commits. The store's compression
        policy applies per bucket (int8 finally meets its
        size-eligibility threshold there). Per-key semantics are
        unchanged: each key commits its unpacked view — epoch bump,
        binding spec, manifest publish — exactly like a per-leaf
        :meth:`push`.

        ``bucketed=False`` is the legacy per-leaf path, kept as the
        parity baseline and escape hatch. Returns ``{key: reduced}``.
        """
        from ptype_tpu.metrics import annotate, metrics

        pairs = _flatten(prefix, stacked_tree)
        if not bucketed:
            return {key: self.push(key, leaf, op) for key, leaf in pairs}

        t0 = _time.perf_counter()
        groups = self._push_groups(pairs, op)
        reduced: dict[str, jax.Array] = {}
        with annotate(f"store.push_tree/{prefix}"):
            # Fault seam INSIDE the region (see push): a straggler
            # delay lands in the collective leg of the goodput ledger
            # and on the push_tree span, not in untracked step time.
            _store_fault("store.push", prefix)
            for group_op, items in groups.items():
                res = self._group_residuals(items)
                site = f"{prefix}|{group_op}"
                ores = self._pop_outer(site)
                try:
                    outs = collectives.bucketed_all_reduce(
                        [leaf for _, leaf in items], self.mesh,
                        self.axis, group_op, residuals=res,
                        outer_residuals=ores,
                        **self._wire_kwargs(bucket_bytes))
                except BaseException:
                    self._restore_residuals(items, res)
                    self._restore_outer(site, ores)
                    raise
                if res is not None:
                    outs, new_res = outs
                    self._store_residuals(items, new_res)
                self._store_outer(site, ores)
                for (key, _), out in zip(items, outs):
                    reduced[key] = out
        # Commit the unpacked views: reshard keys with non-replicated
        # bindings in one batched device_put, then bump epoch + publish
        # manifest per key (the per-key Store contract).
        sharded = [k for k in reduced if self.binding(k).spec != P()]
        if sharded:
            arrs = jax.device_put(
                [reduced[k] for k in sharded],
                [NamedSharding(self.mesh, self.binding(k).spec)
                 for k in sharded])
            reduced.update(zip(sharded, arrs))
        out = {key: self._commit(key, reduced[key], self.binding(key))
               for key, _ in pairs}
        metrics.timing("store.push_tree").observe(
            _time.perf_counter() - t0)
        metrics.counter("store.push_tree.leaves").add(len(pairs))
        chaos.note_ok("store.push", prefix)
        return out

    def _push_groups(self, pairs, op: str | None):
        """Group (key, leaf) pairs by resolved reduce op (dtype
        grouping happens inside the bucket planner); op=None honors
        each key's binding — shared by the barrier and streamed push
        paths so key/op resolution cannot drift between them."""
        groups: dict[str, list[tuple[str, jax.Array]]] = {}
        for key, leaf in pairs:
            resolved = op or self.binding(key).reduce_op
            groups.setdefault(resolved, []).append(
                (key, jnp.asarray(leaf)))
        return groups

    def _wire_kwargs(self, bucket_bytes: int | None) -> dict:
        kw = {
            "bucket_bytes": bucket_bytes or self.wire.bucket_bytes,
            "compress": self.compress,
            "int8_min_bytes": self.wire.int8_min_bytes,
            "q_block": self.wire.q_block,
        }
        if self.topology is not None:
            kw["topology"] = self.topology
        return kw

    def _commit_reduced(self, key: str, out: jax.Array) -> jax.Array:
        """Reshard to the key's binding (if any) and commit — the
        per-key tail both push paths share."""
        kb = self.binding(key)
        if kb.spec != P():
            out = jax.device_put(out, NamedSharding(self.mesh, kb.spec))
        return self._commit(key, out, kb)

    def _group_residuals(self, items) -> list | None:
        """Per-leaf EF residuals for one push group (None when the
        wire doesn't carry feedback). Missing/stale-shape entries stay
        None — the collectives layer seeds zeros.

        Residuals are POPPED, not read: taking ownership under the
        lock means a concurrent pusher of the same key folds zeros
        instead of double-applying the same accumulated error (each
        concurrent push then writes back its own fresh residual)."""
        if not self._feedback_armed():
            return None
        with self._lock:
            return [self._residuals.pop(key, None) for key, _ in items]

    def _feedback_armed(self) -> bool:
        """Per-leaf EF is armed when the flat wire is int8+EF, OR when
        a topology's INNER leg resolves to int8 while the flat policy
        is exact (a LegWire override) — the inner leg owns the
        per-leaf residual in the hierarchical decomposition."""
        if self.wire.feedback_armed:
            return True
        t = self.topology
        if t is None or not self.wire.error_feedback:
            return False
        cw, _ = t.resolve_leg("inner", self.compress, self.wire.q_block)
        return cw == "int8"

    def _store_residuals(self, items, new_res: list) -> None:
        with self._lock:
            for (key, _), r in zip(items, new_res):
                if r is not None:
                    self._residuals[key] = r

    def _restore_residuals(self, items, popped: list | None) -> None:
        """Put popped-but-unconsumed residuals back (a push that
        failed between pop and store-back must not drop the
        accumulated error). setdefault: never clobber a fresher
        residual a concurrent pusher wrote meanwhile."""
        if popped is None:
            return
        with self._lock:
            for (key, _), r in zip(items, popped):
                if r is not None:
                    self._residuals.setdefault(key, r)

    def _outer_armed(self) -> bool:
        """Whether the topology's OUTER (cross-domain) leg carries an
        int8 wire with error feedback — the only case the per-bucket
        outer residual dict is worth threading through a push."""
        t = self.topology
        if t is None or not self.wire.error_feedback:
            return False
        cw, _ = t.resolve_leg("outer", self.compress, self.wire.q_block)
        return cw == "int8"

    def _pop_outer(self, site: str) -> dict | None:
        """Take ownership of a push site's outer-leg residual dict
        (popped under the lock, same two-phase discipline as
        :meth:`_group_residuals`): the collectives stream mutates it
        in place per bucket; store it back when the push completes."""
        if not self._outer_armed():
            return None
        with self._lock:
            return self._outer_residuals.pop(site, {})

    def _store_outer(self, site: str, ores: dict | None) -> None:
        """Write back a consumed outer residual dict; our entries are
        freshest for every bucket this push actually ran, so they
        clobber (mirror of :meth:`_store_residuals`)."""
        if ores:
            with self._lock:
                self._outer_residuals.setdefault(site, {}).update(ores)

    def _restore_outer(self, site: str, ores: dict | None) -> None:
        """Failure path: put popped-but-possibly-unconsumed entries
        back without clobbering a concurrent pusher's fresher ones
        (mirror of :meth:`_restore_residuals`)."""
        if ores:
            with self._lock:
                cur = self._outer_residuals.setdefault(site, {})
                for bi, r in ores.items():
                    cur.setdefault(bi, r)

    def push_tree_iter(self, prefix: str, stacked_tree,
                       op: str | None = None, *,
                       bucket_bytes: int | None = None):
        """The fine-grained-overlap variant of :meth:`push_tree`
        (T3 pattern, PAPERS.md): a generator that dispatches ONE
        bucket's fused collective per iteration, commits its keys, and
        yields the :class:`BucketPush` — so a consumer can interleave
        its own dispatches (per-bucket optimizer apply) and waits with
        the remaining buckets' dispatches, putting reduce i+1 on the
        wire while bucket i is being consumed. Same per-key
        epoch/manifest/residual semantics as push_tree."""
        from ptype_tpu.metrics import annotate, metrics

        pairs = _flatten(prefix, stacked_tree)
        t0 = _time.perf_counter()
        groups = self._push_groups(pairs, op)
        # Each bucket's dispatch+commit runs in its OWN annotate region
        # (not one region held open across yields): the consumer's
        # work between buckets — optimizer applies, waits — must land
        # in its own legs of the goodput breakdown, not inflate the
        # collective leg here.
        first = True
        for group_op, items in groups.items():
            res = self._group_residuals(items)
            site = f"{prefix}|{group_op}"
            ores = self._pop_outer(site)
            done = False
            # The pop in _group_residuals took ownership of every
            # carried residual in the group — track the ones no int8
            # bucket has consumed yet, and RESTORE them when the
            # stream ends (or is abandoned mid-way): a bucket whose
            # wire resolved exact, or one the consumer never drained,
            # must not silently lose its accumulated error.
            pending = ({i: r for i, r in enumerate(res)
                        if r is not None} if res is not None else {})
            try:
                it = collectives.bucketed_all_reduce_stream(
                    [leaf for _, leaf in items], self.mesh,
                    self.axis, group_op, residuals=res,
                    outer_residuals=ores,
                    **self._wire_kwargs(bucket_bytes))
                while True:
                    with annotate(f"store.push_tree/{prefix}"):
                        if first:
                            # Fault seam INSIDE the region (see push):
                            # a straggler delay lands in the
                            # collective leg.
                            _store_fault("store.push", prefix)
                            first = False
                        try:
                            b, outs, new_res = next(it)
                        except StopIteration:
                            break
                        keys, vals = [], []
                        for i, (s, out) in enumerate(zip(b.slots, outs)):
                            key = items[s.index][0]
                            vals.append(self._commit_reduced(key, out))
                            keys.append(key)
                            if new_res is not None:
                                pending.pop(s.index, None)
                                if new_res[i] is not None:
                                    with self._lock:
                                        self._residuals[key] = new_res[i]
                        handle = BucketPush(prefix, keys, vals)
                    yield handle
                done = True
            finally:
                # Outer-leg residuals: the stream updated the popped
                # dict in place for every bucket it ran; clobber-store
                # on a full drain, setdefault-restore on abandonment.
                (self._store_outer if done
                 else self._restore_outer)(site, ores)
                if pending:
                    with self._lock:
                        for i, r in pending.items():
                            # setdefault: never clobber a fresher
                            # residual a concurrent pusher wrote.
                            self._residuals.setdefault(items[i][0], r)
        metrics.timing("store.push_tree").observe(
            _time.perf_counter() - t0)
        metrics.counter("store.push_tree.leaves").add(len(pairs))
        chaos.note_ok("store.push", prefix)

    def push_tree_scatter_iter(self, prefix: str, stacked_tree,
                               op: str | None = None, *,
                               bucket_bytes: int | None = None):
        """The ZeRO gradient leg (parallel/zero.py): reduce-SCATTER
        every bucket of a stacked pytree instead of allreducing it —
        half the wire bytes, each device left holding one contiguous
        flat shard per bucket, committed under
        ``<prefix>/bucketNNNNN`` with a ``P(axis)`` binding (the Store
        contract at bucket granularity: epoch bump + manifest publish
        per scatter, pullable with ``gather=True``). A generator like
        :meth:`push_tree_iter`: one fused collective dispatched per
        iteration, yielding :class:`ShardPush` handles so the consumer
        (the shard-local optimizer apply) interleaves with the
        remaining buckets' dispatches.

        Error-feedback residuals ride the int8 wire exactly like the
        allreduce paths, keyed per LEAF (ownership is uniform across
        push_tree/push_tree_iter/scatter — a trainer switching modes
        carries its accumulated error along); with no all_gather leg,
        the residual is the phase-1 error of this replica's whole
        contribution.
        """
        from ptype_tpu.metrics import annotate, metrics

        pairs = _flatten(prefix, stacked_tree)
        t0 = _time.perf_counter()
        groups = self._push_groups(pairs, op)
        first = True
        bucket_no = 0
        for group_op, items in groups.items():
            res = self._group_residuals(items)
            site = f"{prefix}|{group_op}"
            ores = self._pop_outer(site)
            done = False
            pending = ({i: r for i, r in enumerate(res)
                        if r is not None} if res is not None else {})
            try:
                it = collectives.bucketed_reduce_scatter_stream(
                    [leaf for _, leaf in items], self.mesh,
                    self.axis, group_op, residuals=res,
                    outer_residuals=ores,
                    **self._wire_kwargs(bucket_bytes))
                while True:
                    with annotate(f"store.push_tree/{prefix}"):
                        if first:
                            # Fault seam INSIDE the region (see push):
                            # a straggler delay lands in the
                            # collective leg.
                            _store_fault("store.push", prefix)
                            first = False
                        try:
                            b, flat, new_res = next(it)
                        except StopIteration:
                            break
                        key = f"{prefix}/bucket{bucket_no:05d}"
                        leaf_keys = [items[s.index][0]
                                     for s in b.slots]
                        flat = self._commit(
                            key, flat, Binding(P(self.axis), group_op))
                        if new_res is not None:
                            for i, s in enumerate(b.slots):
                                pending.pop(s.index, None)
                                if new_res[i] is not None:
                                    with self._lock:
                                        self._residuals[
                                            items[s.index][0]
                                        ] = new_res[i]
                        handle = ShardPush(prefix, bucket_no, key, b,
                                           leaf_keys, flat)
                        bucket_no += 1
                    yield handle
                done = True
            finally:
                # Outer-leg residuals: clobber-store on a full drain,
                # setdefault-restore on abandonment (see
                # push_tree_iter).
                (self._store_outer if done
                 else self._restore_outer)(site, ores)
                if pending:
                    with self._lock:
                        for i, r in pending.items():
                            # setdefault: never clobber a fresher
                            # residual a concurrent pusher wrote.
                            self._residuals.setdefault(items[i][0], r)
        metrics.timing("store.push_tree").observe(
            _time.perf_counter() - t0)
        metrics.counter("store.push_tree.leaves").add(len(pairs))
        chaos.note_ok("store.push", prefix)

    def push_tree_stream(self, prefix: str, stacked_tree,
                         op: str | None = None, *,
                         bucket_bytes: int | None = None
                         ) -> list[BucketPush]:
        """:meth:`push_tree_iter` drained eagerly: every bucket
        dispatched and committed, handles returned in bucket order —
        for consumers that want all collectives in flight before the
        first wait."""
        return list(self.push_tree_iter(prefix, stacked_tree, op,
                                        bucket_bytes=bucket_bytes))

    def get_tree(self, prefix: str,
                 gather: bool = False) -> dict[str, jax.Array]:
        """All keys under ``prefix/`` as a flat dict. ``gather=True``
        returns fully-replicated views (the allgather lowering of a
        linearizable read), resharded through one batched device_put.

        Runs as a ``store.pull_tree/<prefix>`` region through the
        metrics.annotate seam — profiler timeline + distributed-trace
        span from the one hook (same contract as push_tree)."""
        from ptype_tpu.metrics import annotate

        with annotate(f"store.pull_tree/{prefix}"):
            return self._get_tree(prefix, gather)

    def _get_tree(self, prefix: str,
                  gather: bool = False) -> dict[str, jax.Array]:
        _store_fault("store.pull", prefix)
        sep = prefix + "/"
        with self._lock:
            hits = {k: e.value for k, e in self._entries.items()
                    if k.startswith(sep)}
        if not hits:
            raise NoKeyError(prefix)
        hits = dict(sorted(hits.items()))
        if gather:
            keys = list(hits)
            arrs = jax.device_put(
                [hits[k] for k in keys],
                [NamedSharding(self.mesh, P())] * len(keys))
            hits = dict(zip(keys, arrs))
        chaos.note_ok("store.pull", prefix)
        return hits

    # ---------------------------------------------------------- manifest

    def _manifest_key(self, key: str) -> str:
        return f"{TENSOR_PREFIX}/{self.namespace}/{key}"

    def _publish(self, key: str) -> None:
        """Best-effort manifest publish + catch-up of earlier misses.

        Manifests are DISCOVERY metadata; the tensors themselves are
        device-resident and the collectives never touch the
        coordinator. A control-plane outage (e.g. the seed dying
        before its standby promotes) must lag the manifest, not kill
        the training step. Keys whose publish failed are remembered
        and republished on the next successful KV contact — a key
        put exactly once (params) self-heals too, not just re-pushed
        gradient keys.
        """
        if self._kv is None:
            return
        if not self._try_publish(key):
            return
        with self._lock:
            missed = [k for k in self._manifest_failed
                      if k != key and k in self._entries]
        recovered = [k for k in missed if self._try_publish(k)]
        if recovered:
            log.info("manifest publishing recovered",
                     kv={"republished": len(recovered)})

    def _try_publish(self, key: str) -> bool:
        with self._lock:
            entry = self._entries[key]
        try:
            self._kv.put(
                self._manifest_key(key),
                json.dumps({
                    "shape": list(entry.value.shape),
                    "dtype": str(entry.value.dtype),
                    "spec": spec_to_json(entry.binding.spec),
                    "epoch": entry.epoch,
                }, separators=(",", ":")),
            )
        except CoordinationError as e:
            with self._lock:
                self._manifest_failed.add(key)
            log.warning("manifest publish failed; will retry on next "
                        "successful publish",
                        kv={"key": key, "err": str(e)})
            return False
        with self._lock:
            self._manifest_failed.discard(key)
        return True

    def manifest(self) -> dict[str, dict]:
        """Key → {shape, dtype, spec, epoch} for the whole namespace —
        what a checkpointer or late joiner reads to discover the space."""
        out = {}
        with self._lock:
            for key, entry in self._entries.items():
                out[key] = {
                    "shape": list(entry.value.shape),
                    "dtype": str(entry.value.dtype),
                    "spec": spec_to_json(entry.binding.spec),
                    "epoch": entry.epoch,
                }
        return out


def _flatten(prefix: str, tree) -> list[tuple[str, jax.Array]]:
    """Pytree → sorted (key, leaf) pairs with path-derived key names."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for path, leaf in leaves:
        parts = [prefix] + [_path_part(p) for p in path]
        out.append(("/".join(parts), leaf))
    return sorted(out)


def _path_part(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


# ---------------------------------------------------------------- benching


def measure_push_tree(mesh: Mesh, axis="data",
                      preset: str = "tiny", iters: int = 3,
                      compress: str | None = None,
                      bucket_bytes: int | None = None,
                      wire: collectives.WireConfig | None = None,
                      topology: Topology | None = None) -> dict:
    """Wall-clock a full param-tree gradient push, bucketed vs
    per-leaf — the BENCH ``store_push_tree_ms`` metric.

    Builds the ``preset`` transformer's parameter tree, fakes stacked
    per-worker grads (each device holding one contribution), and times
    ``push_tree`` both ways after a warm/compile pass. The scalar
    readback per drain is deliberate: ``block_until_ready`` does not
    drain the axon device tunnel (docs/PERF.md measurement gotcha).
    """
    from ptype_tpu.models import transformer as tfm

    cfg = tfm.preset(preset)
    params = jax.jit(lambda r: tfm.init_params(r, cfg))(
        jax.random.PRNGKey(0))
    n = axis_n(mesh, axis)
    stacked = jax.tree_util.tree_map(
        lambda p: jax.device_put(
            jnp.broadcast_to(p[None], (n, *p.shape)),
            NamedSharding(mesh, P(axis, *(None,) * p.ndim))),
        params)
    store = TensorStore(mesh, axis, compress=compress, wire=wire,
                        topology=topology)
    leaves = jax.tree_util.tree_leaves(params)
    nbytes = sum(v.size * v.dtype.itemsize for v in leaves)

    def drain(out: dict) -> None:
        for v in out.values():
            v.block_until_ready()
        float(jnp.sum(next(iter(out.values()))))

    def timed(bucketed: bool) -> float:
        drain(store.push_tree("g", stacked, op="mean",
                              bucketed=bucketed,
                              bucket_bytes=bucket_bytes))  # compile+warm
        t0 = _time.perf_counter()
        for _ in range(iters):
            out = store.push_tree("g", stacked, op="mean",
                                  bucketed=bucketed,
                                  bucket_bytes=bucket_bytes)
        drain(out)
        return (_time.perf_counter() - t0) / iters

    per_leaf = timed(False)
    bucketed = timed(True)
    plan = collectives.plan_buckets(
        jax.tree_util.tree_leaves(stacked), n,
        bucket_bytes or collectives.DEFAULT_BUCKET_BYTES)
    return {
        "bucketed_ms": round(bucketed * 1e3, 2),
        "per_leaf_ms": round(per_leaf * 1e3, 2),
        "speedup": round(per_leaf / bucketed, 2) if bucketed else None,
        "n_leaves": len(leaves),
        "n_buckets": len(plan),
        "payload_mb": round(nbytes / 2**20, 2),
        # Ring allreduce moves 2*(n-1)/n of the buffer per device.
        "gbps": round(2 * (n - 1) / n * nbytes / bucketed / 1e9, 2),
    }
