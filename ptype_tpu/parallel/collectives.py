"""Compiled XLA collectives over mesh axes — the ICI data plane.

The reference's data plane was gob-encoded ``net/rpc`` over TCP
(cluster/rpc.go:277); here the equivalent primitive set is XLA collectives
compiled over ICI (SURVEY.md §2 "Distributed communication backend").
These wrappers give the *eager* entry points the TensorStore and benches
use; inside a jit'ed train step you use ``jax.lax`` collectives (under
``shard_map``) or let GSPMD insert them from sharding annotations.

Conventions: the "stacked" layout carries one leading contribution axis of
size ``mesh.shape[axis]``, sharded over ``axis`` — the eager analog of
per-worker values in a multi-controller program.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ptype_tpu.compat import axis_size, shard_map
from ptype_tpu.parallel.mesh import axis_n
from ptype_tpu.parallel.topology import (INNER_AXIS, OUTER_AXIS,
                                         Topology)

_REDUCERS = ("sum", "mean", "max", "min")


def _rest(ndim: int) -> tuple[None, ...]:
    return (None,) * (ndim - 1)


@functools.lru_cache(maxsize=256)
def _all_reduce_fn(mesh: Mesh, axis: str, ndim: int, op: str):
    in_spec = P(axis, *_rest(ndim))
    out_spec = P(*_rest(ndim))

    def f(local):
        x = jnp.squeeze(local, axis=0)
        if op == "sum":
            return lax.psum(x, axis)
        if op == "mean":
            return lax.pmean(x, axis)
        if op == "max":
            return lax.pmax(x, axis)
        return lax.pmin(x, axis)

    return jax.jit(
        shard_map(f, mesh=mesh, in_specs=in_spec, out_specs=out_spec)
    )


def all_reduce(stacked: jax.Array, mesh: Mesh, axis: str = "data",
               op: str = "sum") -> jax.Array:
    """Reduce per-worker contributions; result replicated over ``axis``.

    ``stacked``: shape ``(mesh.shape[axis], *rest)``, sharded on dim 0.
    Returns shape ``rest`` with every device holding the reduction — the
    Store push lowering (ref Put store.go:56-62 → psum).
    """
    if op not in _REDUCERS:
        raise ValueError(f"all_reduce: op must be one of {_REDUCERS}")
    n = axis_n(mesh, axis)
    if stacked.shape[0] != n:
        raise ValueError(
            f"all_reduce: leading dim {stacked.shape[0]} != axis size {n}"
        )
    stacked = jax.device_put(stacked, NamedSharding(mesh, P(axis, *_rest(stacked.ndim))))
    return _all_reduce_fn(mesh, axis, stacked.ndim, op)(stacked)


@functools.lru_cache(maxsize=256)
def _all_gather_fn(mesh: Mesh, axis: str, ndim: int):
    spec = P(axis, *_rest(ndim))

    def f(local):
        return lax.all_gather(jnp.squeeze(local, axis=0), axis)

    # all_gather's output is replicated by construction, but the varying-
    # manual-axes check cannot infer that — disable it for this wrapper.
    return jax.jit(
        shard_map(f, mesh=mesh, in_specs=spec,
                  out_specs=P(*_rest(ndim + 1)), check_vma=False)
    )


def all_gather(stacked: jax.Array, mesh: Mesh, axis: str = "data") -> jax.Array:
    """Gather per-worker contributions to every device, replicated.

    ``(n, *rest)`` sharded on dim 0 → ``(n, *rest)`` replicated — the Store
    pull lowering (ref Get store.go:38-53 → allgather).
    """
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P(axis, *_rest(stacked.ndim)))
    )
    return _all_gather_fn(mesh, axis, stacked.ndim)(stacked)


@functools.lru_cache(maxsize=256)
def _reduce_scatter_fn(mesh: Mesh, axis: str, ndim: int, op: str):
    in_spec = P(axis, *_rest(ndim))
    # Output keeps rank ndim-1; dim 0 of the payload is scattered.
    out_spec = P(axis, *_rest(ndim - 1))

    def f(local):
        x = jnp.squeeze(local, axis=0)
        n = axis_size(axis)
        red = lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
        if op == "mean":
            red = red / n
        return red

    return jax.jit(shard_map(f, mesh=mesh, in_specs=in_spec, out_specs=out_spec))


def reduce_scatter(stacked: jax.Array, mesh: Mesh, axis: str = "data",
                   op: str = "sum") -> jax.Array:
    """Reduce contributions, leaving each device one shard of the result.

    ``(n, *payload)`` with ``payload[0] % n == 0`` → ``payload`` sharded on
    dim 0 over ``axis``. Half the ICI bytes of an all_reduce when the
    consumer is itself sharded (ZeRO/FSDP-style grad reduction).
    """
    if op not in ("sum", "mean"):
        raise ValueError(
            f"reduce_scatter: op must be 'sum' or 'mean', got {op!r}"
        )
    n = axis_n(mesh, axis)
    if stacked.ndim < 2 or stacked.shape[1] % n != 0:
        raise ValueError(
            f"reduce_scatter: payload dim 0 ({stacked.shape[1:]}) must "
            f"divide by axis size {n}"
        )
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P(axis, *_rest(stacked.ndim)))
    )
    return _reduce_scatter_fn(mesh, axis, stacked.ndim, op)(stacked)


@functools.lru_cache(maxsize=256)
def _ring_shift_fn(mesh: Mesh, axis: str, ndim: int, shift: int):
    spec = P(axis, *_rest(ndim))

    def f(local):
        n = axis_size(axis)
        perm = [(i, (i + shift) % n) for i in range(n)]
        return lax.ppermute(local, axis, perm)

    return jax.jit(shard_map(f, mesh=mesh, in_specs=spec, out_specs=spec))


def ring_shift(stacked: jax.Array, mesh: Mesh, axis: str = "data",
               shift: int = 1) -> jax.Array:
    """Rotate shards around the ``axis`` ring by ``shift`` (ppermute) —
    the building block of ring attention (SURVEY.md §5 long-context)."""
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P(axis, *_rest(stacked.ndim)))
    )
    return _ring_shift_fn(mesh, axis, stacked.ndim, shift)(stacked)


@functools.lru_cache(maxsize=256)
def _all_to_all_fn(mesh: Mesh, axis: str, ndim: int):
    spec = P(axis, *_rest(ndim))

    def f(local):
        # local: (1, n*chunk, *rest) → exchange chunks around the axis.
        x = jnp.squeeze(local, axis=0)
        out = lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)
        return out[None]

    return jax.jit(shard_map(f, mesh=mesh, in_specs=spec, out_specs=spec))


def all_to_all(stacked: jax.Array, mesh: Mesh, axis: str = "data") -> jax.Array:
    """Transpose shard ownership: device i's chunk j goes to device j —
    the EP/Ulysses exchange. ``(n, n*chunk, *rest)`` sharded on dim 0."""
    n = axis_n(mesh, axis)
    if stacked.ndim < 2 or stacked.shape[1] % n != 0:
        raise ValueError(
            f"all_to_all: payload dim 0 must divide by axis size {n}"
        )
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P(axis, *_rest(stacked.ndim)))
    )
    return _all_to_all_fn(mesh, axis, stacked.ndim)(stacked)


#: Default elements per quantization scale block (EQuARX pattern,
#: PAPERS.md arXiv 2506.17615): small enough that one outlier poisons
#: ~0.2% of a bucket instead of a whole all_to_all chunk, large enough
#: that the f32 scale overhead stays <1% of the int8 wire bytes.
DEFAULT_QUANT_BLOCK = 512


def _q_int8_blockwise(chunks: jax.Array, block: int | None):
    """Int8-quantize ``chunks: (m, c)`` with one absmax scale per
    ``block`` contiguous elements (``block=None`` → one scale per
    whole chunk — PR 1's coarse granularity, kept for the wire bench
    comparison). Each chunk zero-pads to a block multiple internally;
    zero blocks quantize exactly. Deterministic round-to-nearest —
    collective results must be reproducible across reruns for the
    numerics test tier. Returns ``(q (m, nb, block) int8,
    scales (m, nb) f32)``."""
    m, c = chunks.shape
    block = c if block is None else min(int(block), c)
    pad = (-c) % block
    if pad:
        chunks = jnp.pad(chunks, ((0, 0), (0, pad)))
    b = chunks.reshape(m, -1, block)
    amax = jnp.max(jnp.abs(b), axis=2)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(b.astype(jnp.float32) / scale[:, :, None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _dq_int8_blockwise(q: jax.Array, scale: jax.Array, c: int):
    """Inverse of :func:`_q_int8_blockwise`: ``(m, nb, block)`` int8 +
    ``(m, nb)`` scales → ``(m, c)`` f32 (internal block pad dropped)."""
    out = (q.astype(jnp.float32) * scale[:, :, None])
    return out.reshape(q.shape[0], -1)[:, :c]


def _int8_phase1(x, axis: str, op: str, block: int | None):
    """The int8 reduce-scatter leg, shared by the quantized allreduce
    and the standalone quantized reduce_scatter (one implementation so
    numerics fixes can't drift between them): slice my flat
    contribution into n chunks, quantize each with per-``block``
    absmax scales, all_to_all so device j collects everyone's chunk j,
    dequantize and reduce. Returns this device's reduced f32 chunk
    ``(elems/n,)`` plus the local quantization error ``(n, elems/n)``
    (what error feedback carries to the next step)."""
    n = axis_size(axis)
    c = x.shape[0] // n
    chunks = x.astype(jnp.float32).reshape(n, c)
    q, scale = _q_int8_blockwise(chunks, block)
    err = chunks - _dq_int8_blockwise(q, scale, c)
    q = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    scale = lax.all_to_all(scale, axis, split_axis=0, concat_axis=0,
                           tiled=True)
    red = jnp.sum(_dq_int8_blockwise(q, scale, c), axis=0)
    if op == "mean":
        red = red / n
    return red, err


def _int8_all_reduce_body(x, axis: str, op: str,
                          block: int | None = DEFAULT_QUANT_BLOCK,
                          res=None):
    """Both wire legs of the block-scaled int8 allreduce on one
    device's flat contribution ``x`` (``len(x) % n == 0``): phase 1
    (:func:`_int8_phase1` in sum space), then the all_gather leg —
    re-quantize my reduced chunk with per-block scales, gather,
    dequantize — so every device reassembles the full f32 reduction
    (mean divided at the very end, so both wire legs and the error
    terms live in one space).

    ``res`` arms **error feedback** (EQuARX/EF-SGD): the residual is
    added to the contribution before quantizing, and the returned
    residual carries BOTH legs' quantization error — phase 1's error
    across my whole contribution, plus phase 2's error on the chunk I
    own, folded in at my chunk's offset (I re-own the same chunk next
    step, so adding it to my next contribution cancels it in the
    reduction). Returns ``(out shaped like x, new_res | None)``."""
    n = axis_size(axis)
    c = x.shape[0] // n
    xf = x.astype(jnp.float32)
    if res is not None:
        xf = xf + res.astype(jnp.float32)
    red, err1 = _int8_phase1(xf, axis, "sum", block)
    q2, s2 = _q_int8_blockwise(red[None], block)
    err2 = red - _dq_int8_blockwise(q2, s2, c)[0]
    qg = lax.all_gather(q2[0], axis)                # (n, nb, block)
    sg = lax.all_gather(s2[0], axis)                # (n, nb)
    out = _dq_int8_blockwise(qg, sg, c).reshape(x.shape)
    if op == "mean":
        out = out / n
    if res is None:
        return out, None
    new_res = err1.reshape(x.shape)
    idx = lax.axis_index(axis)
    mine = lax.dynamic_slice(new_res, (idx * c,), (c,)) + err2
    new_res = lax.dynamic_update_slice(new_res, mine, (idx * c,))
    return out, new_res.astype(res.dtype)


@functools.lru_cache(maxsize=256)
def _quantized_all_reduce_fn(mesh: Mesh, axis: str, ndim: int, op: str,
                             block: int | None):
    in_spec = P(axis, *_rest(ndim))
    out_spec = P(*_rest(ndim))

    def f(local):
        x = jnp.squeeze(local, axis=0)
        out, _ = _int8_all_reduce_body(x.reshape(-1), axis, op, block)
        return out.reshape(x.shape).astype(x.dtype)

    return jax.jit(
        shard_map(f, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
                  check_vma=False)
    )


@functools.lru_cache(maxsize=256)
def _quantized_reduce_scatter_fn(mesh: Mesh, axis: str, ndim: int,
                                 op: str, block: int | None):
    in_spec = P(axis, *_rest(ndim))
    out_spec = P(axis, *_rest(ndim - 1))

    def f(local):
        x = jnp.squeeze(local, axis=0)
        red, _ = _int8_phase1(x.reshape(-1), axis, op, block)
        n = axis_size(axis)
        return red.reshape((x.shape[0] // n,) + x.shape[1:]).astype(
            x.dtype)

    return jax.jit(shard_map(f, mesh=mesh, in_specs=in_spec,
                             out_specs=out_spec))


def quantized_reduce_scatter(stacked: jax.Array, mesh: Mesh,
                             axis: str = "data",
                             op: str = "sum", *,
                             q_block: int | None = DEFAULT_QUANT_BLOCK
                             ) -> jax.Array:
    """Phase 1 of :func:`quantized_all_reduce` alone: int8-quantized
    all_to_all + local dequant-reduce — each device keeps ONE f32
    shard of the reduced tensor (the bandwidth-optimal int8 grad
    reduction for consumers that are themselves sharded, ZeRO/FSDP
    style). Same shape contract and error bound as the allreduce's
    first phase (one round-to-nearest quantization)."""
    n = axis_n(mesh, axis)
    if not quantized_all_reduce_eligible(stacked.shape, n, op):
        raise ValueError(
            f"quantized_reduce_scatter: need op in sum/mean (got "
            f"{op!r}), leading dim == axis size {n} (got "
            f"{stacked.shape[0]}), and payload dim 0 to divide by {n} "
            f"(got {stacked.shape[1:]})")
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P(axis, *_rest(stacked.ndim))))
    return _quantized_reduce_scatter_fn(mesh, axis, stacked.ndim, op,
                                        q_block)(stacked)


def quantized_all_reduce_eligible(shape: tuple, n: int,
                                  op: str) -> bool:
    """Whether a stacked ``(n, *rest)`` payload can take the int8 path
    — the single source of its constraints (callers like TensorStore
    route ineligible leaves to the exact allreduce)."""
    return (op in ("sum", "mean") and len(shape) >= 2
            and shape[0] == n and shape[1] % n == 0)


def quantized_all_reduce(stacked: jax.Array, mesh: Mesh,
                         axis: str = "data",
                         op: str = "sum", *,
                         q_block: int | None = DEFAULT_QUANT_BLOCK
                         ) -> jax.Array:
    """Block-scaled int8 allreduce — the EQuARX pattern (PAPERS.md):
    both wire phases of the bandwidth-optimal allreduce decomposition
    (all_to_all reduce-scatter, then all_gather) carry int8 payloads
    with one f32 absmax scale per ``q_block`` elements, ≈4× fewer ICI
    bytes than f32 at a bounded relative error (two round-to-nearest
    quantizations of ≤ block-absmax/254 each — an outlier poisons one
    block, not the whole chunk). ``q_block=None`` falls back to one
    scale per all_to_all chunk (the PR 1 wire, kept for comparison).
    Lossy: for gradients, not parameters.

    ``stacked``: ``(axis_size, *rest)`` with ``rest[0] % axis_size
    == 0``; returns ``rest``, replicated.
    """
    n = axis_n(mesh, axis)
    if not quantized_all_reduce_eligible(stacked.shape, n, op):
        raise ValueError(
            f"quantized_all_reduce: need op in sum/mean (got {op!r}), "
            f"leading dim == axis size {n} (got {stacked.shape[0]}), "
            f"and payload dim 0 to divide by {n} "
            f"(got {stacked.shape[1:]})")
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P(axis, *_rest(stacked.ndim))))
    return _quantized_all_reduce_fn(mesh, axis, stacked.ndim, op,
                                    q_block)(stacked)


def broadcast(value: jax.Array, mesh: Mesh) -> jax.Array:
    """Replicate a host/single-device value across the whole mesh."""
    return jax.device_put(value, NamedSharding(mesh, P()))


# ------------------------------------------------- bucketed tree collectives
#
# A pytree pushed leaf-by-leaf costs one XLA launch per leaf — ~100
# eager collectives for optimus-125M, which is why BENCH_r05's
# store_allreduce_gbps (one big fused buffer) is unreachable from the
# per-leaf push_tree path. The bucketing layer packs same-dtype leaves
# into large flat buckets (EQuARX: quantized collectives only pay off
# on large fused buffers; T3: overlap the reduction instead of
# serializing per-leaf round trips) and runs ONE fused collective per
# bucket inside a single jit'd shard_map program. Buckets dispatch
# asynchronously — the host races ahead and issues every bucket before
# the first finishes, so reduction overlaps host work and later compute.

#: Default per-device payload target per bucket. Big enough that launch
#: overhead and per-collective latency amortize; small enough that the
#: first bucket's reduction overlaps the packing of the rest.
DEFAULT_BUCKET_BYTES = 32 * 1024 * 1024

#: Buckets below this per-device payload ride the EXACT allreduce even
#: under compress="int8": at small sizes the quantize/dequantize math
#: and the second collective leg cost more than the wire bytes saved.
INT8_MIN_BUCKET_BYTES = 64 * 1024


@dataclasses.dataclass(frozen=True)
class WireConfig:
    """One place for the gradient-wire policy, plumbed from the
    trainers through :class:`~ptype_tpu.parallel.tensorstore.
    TensorStore` down to the bucketed collectives.

    ``compress``: None (exact) | "bf16" | "int8" (block-scaled).
    ``q_block``: elements per int8 scale block (None = one scale per
    all_to_all chunk — the PR 1 wire, kept for benches).
    ``error_feedback``: carry a per-leaf residual of the quantization
    error into the next push (int8 wire only) so error does not
    accumulate across steps.
    """

    compress: str | None = None
    q_block: int | None = DEFAULT_QUANT_BLOCK
    error_feedback: bool = True
    bucket_bytes: int = DEFAULT_BUCKET_BYTES
    int8_min_bytes: int = INT8_MIN_BUCKET_BYTES

    def __post_init__(self):
        if self.compress not in (None, "bf16", "int8"):
            raise ValueError(
                f"WireConfig: unknown compression {self.compress!r}")
        # Floor of 8: below that the 4-byte f32 scale per block costs
        # more than the 3 bytes/element int8 saves (at q_block=1 the
        # "compressed" wire is 5 bytes/elem vs fp32's 4 — lossy AND
        # bigger). A config typo must fail here, not ship that.
        if self.q_block is not None and self.q_block < 8:
            raise ValueError(
                f"WireConfig: q_block must be None or >= 8 (the f32 "
                f"scale overhead is 4/q_block bytes per element), got "
                f"{self.q_block!r}")

    @property
    def feedback_armed(self) -> bool:
        return self.compress == "int8" and self.error_feedback


@dataclasses.dataclass(frozen=True)
class LeafSlot:
    """One leaf's location inside a bucket's flat per-device payload."""

    index: int            # position in the caller's flat leaf list
    offset: int           # element offset into the bucket payload
    size: int             # payload elements (per device)
    shape: tuple          # per-device payload shape (``rest``)


@dataclasses.dataclass(frozen=True)
class Bucket:
    """A dtype-homogeneous pack of leaves reduced as one flat buffer."""

    dtype: str            # numpy dtype name — the grouping key
    slots: tuple          # tuple[LeafSlot, ...], ascending offsets
    pad: int              # zero elements appended so elems % n == 0

    @property
    def elems(self) -> int:
        last = self.slots[-1]
        return last.offset + last.size + self.pad

    @property
    def payload_bytes(self) -> int:
        return (self.elems - self.pad) * jnp.dtype(self.dtype).itemsize


def plan_buckets(leaves, n: int,
                 bucket_bytes: int = DEFAULT_BUCKET_BYTES) -> list[Bucket]:
    """Greedy same-dtype packing of stacked ``(n, *rest)`` leaves.

    Leaves keep their original order within a dtype group; a group's
    open bucket closes when the next leaf would push its per-device
    payload past ``bucket_bytes`` (so a single oversize leaf gets its
    own bucket, and a leaf that would straddle the target starts the
    next bucket instead of splitting). Every bucket's payload is
    zero-padded to a multiple of ``n`` so the scatter and int8 paths
    are always shape-eligible — the per-leaf eligibility lottery
    (``rest[0] % n``) disappears at the bucket level.
    """
    out: list[Bucket] = []
    open_slots: dict[str, list[LeafSlot]] = {}
    open_bytes: dict[str, int] = {}

    def close(dt: str) -> None:
        slots = open_slots.pop(dt, [])
        if slots:
            total = slots[-1].offset + slots[-1].size
            out.append(Bucket(dt, tuple(slots), (-total) % n))
        open_bytes.pop(dt, None)

    for i, leaf in enumerate(leaves):
        shape = tuple(leaf.shape)
        if not shape or shape[0] != n:
            raise ValueError(
                f"plan_buckets: leaf {i} shape {shape} must lead with "
                f"the contribution axis (size {n})")
        dt = jnp.dtype(leaf.dtype).name
        size = 1
        for d in shape[1:]:
            size *= int(d)
        nbytes = size * jnp.dtype(dt).itemsize
        if dt in open_slots and open_bytes[dt] + nbytes > bucket_bytes:
            close(dt)
        slots = open_slots.setdefault(dt, [])
        off = (slots[-1].offset + slots[-1].size) if slots else 0
        slots.append(LeafSlot(i, off, size, shape[1:]))
        open_bytes[dt] = open_bytes.get(dt, 0) + nbytes
    for dt in list(open_slots):
        close(dt)
    return out


def _bucket_wire(bucket: Bucket, op: str, compress: str | None,
                 int8_min_bytes: int) -> str | None:
    """Resolve a bucket's wire format. Non-float buckets always ride
    exact (step counters must not round-trip through bf16/int8 — the
    caller opted into float loss only); int8 additionally needs a
    sum/mean op and enough payload to amortize the quantize legs."""
    if compress is None:
        return None
    if not jnp.issubdtype(jnp.dtype(bucket.dtype), jnp.floating):
        return None
    if compress == "bf16":
        return "bf16"
    # max(..., 1): a zero-element bucket must never quantize — the
    # blockwise kernel's chunk math divides by the block size.
    if op in ("sum", "mean") and \
            bucket.payload_bytes >= max(int8_min_bytes, 1):
        return "int8"
    return None


def _unpack(red, slots):
    """Slice a reduced flat buffer back into leaf views (static offsets
    — XLA fuses these with the collective's output)."""
    return tuple(red[s.offset:s.offset + s.size].reshape(s.shape)
                 for s in slots)


def _slot_offsets(shapes: tuple) -> list:
    """Contiguous :class:`LeafSlot` layout for per-device payload
    ``shapes`` — the ONE offset computation every fused bucket program
    (allreduce, reduce-scatter, the zero shard-apply) unpacks with, so
    the flat layout cannot drift between them."""
    offs = []
    off = 0
    for s in shapes:
        size = 1
        for d in s:
            size *= int(d)
        offs.append(LeafSlot(0, off, size, s))
        off += size
    return offs


def _pack_flat(locals_, pad: int):
    """Squeeze the stacked dim off each per-device leaf, flatten,
    concatenate, and zero-pad to the bucket's padded length — the ONE
    packing both fused bucket programs (allreduce and reduce-scatter)
    share, so the wire layouts cannot drift."""
    parts = [jnp.squeeze(x, axis=0).reshape(-1) for x in locals_]
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


@functools.lru_cache(maxsize=512)
def _bucket_all_reduce_fn(mesh: Mesh, axis: str, op: str, shapes: tuple,
                          dtype: str, pad: int, wire: str | None,
                          restore: bool,
                          q_block: int | None = DEFAULT_QUANT_BLOCK,
                          ef: bool = False):
    """One fused program: pack → (quantize?) → allreduce → unpack.

    ``shapes``: per-device payload shapes of the bucket's leaves, in
    slot order. The whole thing is a single jit'd shard_map, so the
    bucket costs ONE collective launch (two wire legs under int8)
    regardless of leaf count.

    ``ef`` (int8 wire only): the program takes a second set of stacked
    per-leaf residual operands, adds them into the contribution before
    quantizing, and returns updated residuals (stacked layout) after
    the reduced leaves — error feedback fused into the same launch.
    """
    in_specs = tuple(P(axis, *(None,) * len(s)) for s in shapes)
    out_specs = tuple(P(*(None,) * len(s)) for s in shapes)
    if ef:
        in_specs = in_specs + in_specs
        out_specs = out_specs + tuple(
            P(axis, *(None,) * len(s)) for s in shapes)
    offs = _slot_offsets(shapes)

    def f(*locals_):
        flat = _pack_flat(locals_[:len(shapes)], pad)
        if wire == "int8":
            res = _pack_flat(locals_[len(shapes):], pad) if ef else None
            red, new_res = _int8_all_reduce_body(flat, axis, op,
                                                 q_block, res)
        else:
            new_res = None
            w = flat.astype(jnp.bfloat16) if wire == "bf16" else flat
            if op == "sum":
                red = lax.psum(w, axis)
            elif op == "mean":
                red = lax.pmean(w, axis)
            elif op == "max":
                red = lax.pmax(w, axis)
            else:
                red = lax.pmin(w, axis)
        # Restore the leaf dtype only when a wire compression was
        # REQUESTED (per-leaf push semantics): the exact path returns
        # whatever the lax op produces (pmean promotes ints to float).
        if restore:
            red = red.astype(jnp.dtype(dtype))
        out = _unpack(red, offs)
        if not ef:
            return out
        # ef is armed only for int8 buckets (the stream layer's
        # contract) — the body always produced a residual. Zeroing a
        # missing one here would silently WIPE carried error, so fail
        # loudly at trace time instead.
        assert new_res is not None, "ef requires the int8 wire"
        return out + tuple(r[None] for r in _unpack(new_res, offs))

    return jax.jit(shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False))


@functools.lru_cache(maxsize=512)
def _bucket_reduce_scatter_fn(mesh: Mesh, axis: str, op: str,
                              shapes: tuple, dtype: str, pad: int,
                              wire: str | None, restore: bool,
                              q_block: int | None = DEFAULT_QUANT_BLOCK,
                              ef: bool = False):
    """Pack → (quantize?) → reduce-scatter; each device keeps one flat
    ``elems/n`` shard of the bucket (half the allreduce's ICI bytes).

    ``ef`` (int8 wire only): the program takes stacked per-leaf
    error-feedback residual operands, folds them into the contribution
    before quantizing, and returns the new residuals (the phase-1
    quantization error — the scatter has no all_gather leg, so each
    replica owns the error of its WHOLE contribution and cancels it in
    the next step's reduction) after the scattered shard."""
    in_specs = tuple(P(axis, *(None,) * len(s)) for s in shapes)
    out_specs = P(axis)
    if ef:
        in_specs = in_specs + in_specs
        out_specs = (P(axis),) + tuple(
            P(axis, *(None,) * len(s)) for s in shapes)
    offs = _slot_offsets(shapes)

    def f(*locals_):
        flat = _pack_flat(locals_[:len(shapes)], pad)
        if wire == "int8":
            if ef:
                flat = flat.astype(jnp.float32) + _pack_flat(
                    locals_[len(shapes):], pad).astype(jnp.float32)
            shard, err = _int8_phase1(flat, axis, op, q_block)
        else:
            err = None
            w = flat.astype(jnp.bfloat16) if wire == "bf16" else flat
            shard = lax.psum_scatter(w, axis, scatter_dimension=0,
                                     tiled=True)
            if op == "mean":
                shard = shard / axis_size(axis)
        if restore:
            shard = shard.astype(jnp.dtype(dtype))
        if not ef:
            return shard
        # ef is armed only on int8 buckets (the stream layer's
        # contract): a missing residual here would mean carried error
        # silently wiped — fail loudly at trace time.
        assert err is not None, "ef requires the int8 wire"
        new_res = err.reshape(flat.shape).astype(jnp.dtype(dtype))
        return (shard,) + tuple(r[None] for r in _unpack(new_res, offs))

    return jax.jit(shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False))


# --------------------------------------- hierarchical (2-D) programs
#
# Real fleets are hierarchical: fast ICI inside a pod (the topology's
# ``inner`` axis), slow DCN between pods (``outer``). The flat ring
# over a 2-D layout crosses domains on ~every hop, so it prices the
# WHOLE payload at the slow leg. The hierarchical decomposition
# (PAPERS.md arXiv 1909.09756) reduce-scatters inside the fast domain,
# exchanges only 1/n_inner of the bytes across the slow leg, and
# allgathers back out — with the int8+EF wire resolved PER LEG
# (EQuARX: quantize the slow hop harder). Error feedback follows the
# flat paths' ownership discipline, per leg:
#
# - the INNER residual is the producer's own phase-1 quantization
#   error across its whole contribution (plus, on the allreduce, its
#   share of the gather-leg error at its own chunk offset — divided by
#   n_outer since every domain's copy of that chunk folds the same
#   deterministic error);
# - the OUTER residual is the error of quantizing the inner-RS chunk
#   this device carries into the cross-domain exchange — it re-owns
#   the same chunk next step, so adding it back pre-quantize cancels
#   it in the next reduction. It is a per-bucket FLAT vector (chunk
#   boundaries cut across leaf slots), keyed per bucket by callers.


def _hier_allreduce_body(flat, ni: int, no: int, op: str,
                         wire_in, wire_out, qb_in, qb_out,
                         res_in, res_out):
    """Three legs on one device's flat contribution ``flat`` (length
    ``E``, ``E % (ni*no) == 0``): inner reduce-scatter → outer
    exchange of the ``E/ni`` chunk → inner allgather. Size-1 legs are
    skipped (identity), so every (outer, inner) factorization lowers
    through the same body. Returns ``(out (E,), new_res_in (E,) |
    None, new_res_out (E/ni,) | None)``; all error terms live in sum
    space (mean divides at the very end, like the flat bodies)."""
    E = flat.shape[0]
    c1 = E // ni
    quant = wire_in == "int8" or wire_out == "int8"
    xf = flat.astype(jnp.float32) if quant else flat
    if res_in is not None:
        xf = xf + res_in.astype(jnp.float32)
    # -- leg 1: reduce-scatter inside the fast inner domain.
    new_res_in = None
    if ni == 1:
        red = xf
    elif wire_in == "int8":
        red, err1 = _int8_phase1(xf, INNER_AXIS, "sum", qb_in)
        if res_in is not None:
            new_res_in = err1.reshape(xf.shape)
    else:
        w = xf.astype(jnp.bfloat16) if wire_in == "bf16" else xf
        red = lax.psum_scatter(w, INNER_AXIS, scatter_dimension=0,
                               tiled=True)
        if wire_in == "bf16":
            red = red.astype(xf.dtype)
    # -- leg 2: exchange only this 1/ni chunk across the slow leg.
    new_res_out = None
    if no > 1:
        if wire_out == "int8":
            red, new_res_out = _int8_all_reduce_body(
                red, OUTER_AXIS, "sum", qb_out, res_out)
        else:
            w = red.astype(jnp.bfloat16) if wire_out == "bf16" else red
            red = lax.psum(w, OUTER_AXIS)
            if wire_out == "bf16":
                red = red.astype(xf.dtype)
    # -- leg 3: allgather the reduced chunk back out, fast leg again.
    if ni == 1:
        out = red
    elif wire_in == "int8":
        q2, s2 = _q_int8_blockwise(red[None], qb_in)
        err3 = red - _dq_int8_blockwise(q2, s2, c1)[0]
        qg = lax.all_gather(q2[0], INNER_AXIS)
        sg = lax.all_gather(s2[0], INNER_AXIS)
        out = _dq_int8_blockwise(qg, sg, c1).reshape(xf.shape)
        if new_res_in is not None:
            # Every domain holds an identical copy of this chunk and
            # folds the same deterministic gather error — divide by
            # n_outer so the next step's sum corrects it exactly once.
            idx = lax.axis_index(INNER_AXIS)
            mine = lax.dynamic_slice(new_res_in, (idx * c1,), (c1,)) \
                + err3 / no
            new_res_in = lax.dynamic_update_slice(new_res_in, mine,
                                                  (idx * c1,))
    else:
        w = red.astype(jnp.bfloat16) if wire_in == "bf16" else red
        out = lax.all_gather(w, INNER_AXIS, tiled=True)
        if wire_in == "bf16":
            out = out.astype(xf.dtype)
    if op == "mean":
        out = out / (ni * no)
    return out, new_res_in, new_res_out


def _hier_reduce_scatter_body(flat, ni: int, no: int, op: str,
                              wire_in, wire_out, qb_in, qb_out,
                              res_in, res_out):
    """The scatter half of :func:`_hier_allreduce_body` (no gather
    leg): inner reduce-scatter, then outer reduce-scatter of the
    ``E/ni`` chunk. Chunk ordering matches the flat composite-axis
    reduce-scatter exactly, so ZeRO's flat shards ride unchanged.
    Returns ``(shard (E/(ni*no),), new_res_in, new_res_out)``."""
    quant = wire_in == "int8" or wire_out == "int8"
    xf = flat.astype(jnp.float32) if quant else flat
    if res_in is not None:
        xf = xf + res_in.astype(jnp.float32)
    new_res_in = None
    if ni == 1:
        red = xf
    elif wire_in == "int8":
        red, err1 = _int8_phase1(xf, INNER_AXIS, "sum", qb_in)
        if res_in is not None:
            new_res_in = err1.reshape(xf.shape)
    else:
        w = xf.astype(jnp.bfloat16) if wire_in == "bf16" else xf
        red = lax.psum_scatter(w, INNER_AXIS, scatter_dimension=0,
                               tiled=True)
        if wire_in == "bf16":
            red = red.astype(xf.dtype)
    new_res_out = None
    if no > 1:
        if wire_out == "int8":
            rf = red.astype(jnp.float32)
            if res_out is not None:
                rf = rf + res_out.astype(jnp.float32)
            shard, err_o = _int8_phase1(rf, OUTER_AXIS, "sum", qb_out)
            if res_out is not None:
                new_res_out = err_o.reshape(rf.shape)
        else:
            w = red.astype(jnp.bfloat16) if wire_out == "bf16" else red
            shard = lax.psum_scatter(w, OUTER_AXIS,
                                     scatter_dimension=0, tiled=True)
            if wire_out == "bf16":
                shard = shard.astype(xf.dtype)
    else:
        shard = red
    if op == "mean":
        shard = shard / (ni * no)
    return shard, new_res_in, new_res_out


@functools.lru_cache(maxsize=512)
def _hier_bucket_all_reduce_fn(mesh: Mesh, op: str, shapes: tuple,
                               dtype: str, pad: int,
                               wire_in, wire_out, restore: bool,
                               qb_in, qb_out,
                               ef_in: bool = False,
                               ef_out: bool = False):
    """Hierarchical counterpart of :func:`_bucket_all_reduce_fn`: ONE
    fused program per bucket over the 2-D mesh — inner reduce-scatter,
    outer exchange, inner allgather, with per-leg wire formats and
    per-leg error-feedback operands. Operand order: ``*leaves``
    (stacked over the composite axis), then stacked inner residuals
    when ``ef_in``, then the flat outer residual (global ``(n * E/ni,)``
    f32, sharded over the composite axis) when ``ef_out``. Outputs
    mirror: reduced leaves, new inner residuals, new outer residual."""
    ax = (INNER_AXIS, OUTER_AXIS)
    ni = int(mesh.shape[INNER_AXIS])
    no = int(mesh.shape[OUTER_AXIS])
    stacked = tuple(P(ax, *(None,) * len(s)) for s in shapes)
    in_specs = stacked
    out_specs = tuple(P(*(None,) * len(s)) for s in shapes)
    if ef_in:
        in_specs = in_specs + stacked
        out_specs = out_specs + stacked
    if ef_out:
        in_specs = in_specs + (P(ax),)
        out_specs = out_specs + (P(ax),)
    offs = _slot_offsets(shapes)
    k = len(shapes)

    def f(*locals_):
        flat = _pack_flat(locals_[:k], pad)
        pos = k
        res_in = None
        if ef_in:
            res_in = _pack_flat(locals_[pos:pos + k], pad)
            pos += k
        res_out = locals_[pos] if ef_out else None
        out, nri, nro = _hier_allreduce_body(
            flat, ni, no, op, wire_in, wire_out, qb_in, qb_out,
            res_in, res_out)
        if restore:
            out = out.astype(jnp.dtype(dtype))
        outs = _unpack(out, offs)
        if ef_in:
            # ef_in is armed only with the int8 inner leg (the stream
            # layer's contract) — a missing residual would silently
            # wipe carried error; fail loudly at trace time.
            assert nri is not None, "ef_in requires the int8 inner leg"
            outs = outs + tuple(
                r[None] for r in _unpack(
                    nri.astype(jnp.dtype(dtype)), offs))
        if ef_out:
            assert nro is not None, "ef_out requires the int8 outer leg"
            outs = outs + (nro.astype(jnp.float32),)
        return outs

    return jax.jit(shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False))


@functools.lru_cache(maxsize=512)
def _hier_bucket_reduce_scatter_fn(mesh: Mesh, op: str, shapes: tuple,
                                   dtype: str, pad: int,
                                   wire_in, wire_out, restore: bool,
                                   qb_in, qb_out,
                                   ef_in: bool = False,
                                   ef_out: bool = False):
    """Hierarchical counterpart of :func:`_bucket_reduce_scatter_fn`:
    inner reduce-scatter then outer reduce-scatter of the chunk —
    each device ends with the SAME flat ``elems/n`` shard the flat
    composite-axis scatter would give it (ZeRO consumes it
    unchanged). Same operand/result order as the hier allreduce,
    with the scattered flat shard first."""
    ax = (INNER_AXIS, OUTER_AXIS)
    ni = int(mesh.shape[INNER_AXIS])
    no = int(mesh.shape[OUTER_AXIS])
    stacked = tuple(P(ax, *(None,) * len(s)) for s in shapes)
    in_specs = stacked
    out_specs: tuple = (P(ax),)
    if ef_in:
        in_specs = in_specs + stacked
        out_specs = out_specs + stacked
    if ef_out:
        in_specs = in_specs + (P(ax),)
        out_specs = out_specs + (P(ax),)
    offs = _slot_offsets(shapes)
    k = len(shapes)

    def f(*locals_):
        flat = _pack_flat(locals_[:k], pad)
        pos = k
        res_in = None
        if ef_in:
            res_in = _pack_flat(locals_[pos:pos + k], pad)
            pos += k
        res_out = locals_[pos] if ef_out else None
        shard, nri, nro = _hier_reduce_scatter_body(
            flat, ni, no, op, wire_in, wire_out, qb_in, qb_out,
            res_in, res_out)
        if restore:
            shard = shard.astype(jnp.dtype(dtype))
        outs = (shard,)
        if ef_in:
            assert nri is not None, "ef_in requires the int8 inner leg"
            outs = outs + tuple(
                r[None] for r in _unpack(
                    nri.astype(jnp.dtype(dtype)), offs))
        if ef_out:
            assert nro is not None, "ef_out requires the int8 outer leg"
            outs = outs + (nro.astype(jnp.float32),)
        return outs if len(outs) > 1 else outs[0]

    return jax.jit(shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=(out_specs if ef_in or ef_out
                                        else out_specs[0]),
                             check_vma=False))


def _wire_scale(wire, q_block, itemsize: int) -> float:
    """Bytes-on-the-wire multiplier of a leg's format vs the bucket's
    native dtype (f32 scale overhead included for int8)."""
    if wire == "bf16":
        return 2.0 / itemsize
    if wire == "int8":
        qb = q_block if q_block else DEFAULT_QUANT_BLOCK
        return (1.0 + 4.0 / qb) / itemsize
    return 1.0


def _resolve_leg_wires(topo: Topology, bucket: Bucket, op: str,
                       compress, int8_min_bytes, q_block):
    """Per-leg wire resolution for one bucket: the topology's leg
    policy overrides the caller's flat setting, then the bucket-level
    eligibility gate (:func:`_bucket_wire`) applies per leg, and
    size-1 legs are forced exact (their collectives are skipped)."""
    c_in, qb_in = topo.resolve_leg(INNER_AXIS, compress, q_block)
    c_out, qb_out = topo.resolve_leg(OUTER_AXIS, compress, q_block)
    wire_in = _bucket_wire(bucket, op, c_in, int8_min_bytes)
    wire_out = _bucket_wire(bucket, op, c_out, int8_min_bytes)
    if int(topo.n_inner) == 1:
        wire_in = None
    if int(topo.n_outer) == 1:
        wire_out = None
    restore = c_in is not None or c_out is not None
    return wire_in, wire_out, qb_in, qb_out, restore


def _count_leg_bytes(topo: Topology, bucket: Bucket, kind: str,
                     wire_in, wire_out, qb_in, qb_out) -> None:
    """Analytic per-leg wire-byte accounting for one hierarchical
    bucket launch — the metrics family ``obs topo`` and the bench
    read. Bytes are per device, scaled by each leg's wire format."""
    from ptype_tpu.metrics import metrics

    itemsize = jnp.dtype(bucket.dtype).itemsize
    legs = topo.leg_bytes(bucket.elems * itemsize, kind)
    inner = legs["inner"] * _wire_scale(wire_in, qb_in, itemsize)
    outer = legs["outer"] * _wire_scale(wire_out, qb_out, itemsize)
    metrics.counter("collectives.leg_bytes.inner").add(int(inner))
    metrics.counter("collectives.leg_bytes.outer").add(int(outer))
    metrics.counter("collectives.leg_bytes.flat_outer").add(
        int(legs["flat_outer"]))
    metrics.counter("collectives.hier_launches").add(1)


def _seed_outer_residual(outer_residuals, bi: int, want: tuple,
                         mesh: Mesh):
    """Pop bucket ``bi``'s flat outer-leg residual from the caller's
    dict (zeros when absent or shape-stale — a replan changed the
    bucket) and place it sharded over the composite axis."""
    ax = (INNER_AXIS, OUTER_AXIS)
    r = outer_residuals.get(bi)
    if r is None or tuple(r.shape) != want:
        r = jnp.zeros(want, jnp.float32)
    return jax.device_put(r, NamedSharding(mesh, P(ax)))


def bucketed_reduce_scatter_stream(leaves, mesh: Mesh,
                                   axis: str = "data", op: str = "sum",
                                   *,
                                   bucket_bytes: int =
                                   DEFAULT_BUCKET_BYTES,
                                   compress: str | None = None,
                                   int8_min_bytes: int =
                                   INT8_MIN_BUCKET_BYTES,
                                   q_block: int | None =
                                   DEFAULT_QUANT_BLOCK,
                                   residuals: list | None = None,
                                   topology: Topology | None = None,
                                   outer_residuals: dict | None = None):
    """Reduce-scatter counterpart of :func:`bucketed_all_reduce_stream`
    — the gradient leg of the ZeRO-style sharded weight update
    (parallel/zero.py): one fused reduce-scatter per bucket, yielding
    ``(bucket, flat_shard, new_residuals_by_slot | None)`` right after
    the dispatch. ``flat_shard`` is the bucket's reduced flat
    ``(elems,)`` buffer sharded ``P(axis)`` — each device holds its
    contiguous ``elems/n`` shard, half the allreduce's wire bytes and
    exactly the resident form the shard-local optimizer consumes.

    ``residuals``: per-leaf stacked error-feedback residuals aligned
    with ``leaves`` (None entries seed zeros); they engage only on
    buckets whose wire resolves to int8, like the allreduce stream.

    ``topology``: a hierarchical :class:`Topology` routes every bucket
    through the 2-leg decomposition (``axis`` must be the composite
    ``("inner", "outer")`` tuple on the topology's mesh); the shard
    layout is IDENTICAL to the flat path's, so consumers don't change.
    ``outer_residuals``: mutable per-bucket dict of outer-leg EF flats
    — read for the seed, updated in place after each dispatch (leaf
    slots can't carry them: chunk boundaries cut across slots).
    """
    if op not in ("sum", "mean"):
        raise ValueError(
            f"bucketed_reduce_scatter: op must be 'sum' or 'mean', "
            f"got {op!r}")
    if compress not in (None, "bf16", "int8"):
        raise ValueError(
            f"bucketed_reduce_scatter: unknown compression {compress!r}")
    leaves = [jnp.asarray(x) for x in leaves]
    n = axis_n(mesh, axis)
    buckets = plan_buckets(leaves, n, bucket_bytes)
    placed = _place_stacked(leaves, mesh, axis)
    for bi, b in enumerate(buckets):
        if topology is not None:
            wire_in, wire_out, qb_in, qb_out, restore = \
                _resolve_leg_wires(topology, b, op, compress,
                                   int8_min_bytes, q_block)
            ef_in = wire_in == "int8" and residuals is not None
            ef_out = (wire_out == "int8"
                      and outer_residuals is not None)
            fn = _hier_bucket_reduce_scatter_fn(
                mesh, op, tuple(s.shape for s in b.slots), b.dtype,
                b.pad, wire_in, wire_out, restore, qb_in, qb_out,
                ef_in, ef_out)
            args = [placed[s.index] for s in b.slots]
            if ef_in:
                args += _place_stacked(
                    [residuals[s.index]
                     if residuals[s.index] is not None
                     and tuple(residuals[s.index].shape)
                     == tuple(leaves[s.index].shape)
                     else jnp.zeros_like(leaves[s.index])
                     for s in b.slots], mesh, axis)
            if ef_out:
                args.append(_seed_outer_residual(
                    outer_residuals, bi,
                    (b.elems * int(topology.n_outer),), mesh))
            outs = fn(*args)
            _count_launch()
            _count_leg_bytes(topology, b, "reduce_scatter",
                             wire_in, wire_out, qb_in, qb_out)
            if ef_out:
                outer_residuals[bi] = outs[-1]
                outs = outs[:-1]
            if ef_in:
                yield b, outs[0], list(outs[1:])
            elif ef_out:
                yield b, outs[0], None
            else:
                yield b, outs, None
            continue
        wire = _bucket_wire(b, op, compress, int8_min_bytes)
        ef = wire == "int8" and residuals is not None
        fn = _bucket_reduce_scatter_fn(
            mesh, axis, op, tuple(s.shape for s in b.slots), b.dtype,
            b.pad, wire, compress is not None, q_block, ef)
        args = [placed[s.index] for s in b.slots]
        if ef:
            args += _place_stacked(
                [residuals[s.index]
                 if residuals[s.index] is not None
                 and tuple(residuals[s.index].shape)
                 == tuple(leaves[s.index].shape)
                 else jnp.zeros_like(leaves[s.index])
                 for s in b.slots], mesh, axis)
        outs = fn(*args)
        _count_launch()
        if ef:
            yield b, outs[0], list(outs[1:])
        else:
            yield b, outs, None


def _count_launch(n: int = 1) -> None:
    from ptype_tpu.metrics import metrics

    metrics.counter("collectives.bucket_launches").add(n)


def _place_stacked(leaves, mesh: Mesh, axis: str):
    """One batched device_put onto the stacked layout (transfers for
    every leaf dispatch together; a no-op for already-placed grads)."""
    shardings = [NamedSharding(mesh, P(axis, *_rest(x.ndim)))
                 for x in leaves]
    return jax.device_put(leaves, shardings)


def bucketed_all_reduce_stream(leaves, mesh: Mesh, axis: str = "data",
                               op: str = "sum", *,
                               bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                               compress: str | None = None,
                               int8_min_bytes: int = INT8_MIN_BUCKET_BYTES,
                               q_block: int | None = DEFAULT_QUANT_BLOCK,
                               residuals: list | None = None,
                               topology: Topology | None = None,
                               outer_residuals: dict | None = None):
    """Generator core of :func:`bucketed_all_reduce`: dispatches one
    fused collective per bucket and yields
    ``(bucket, reduced_by_slot, new_residuals_by_slot | None)`` right
    after that bucket's dispatch — the T3-style consumption surface
    (PAPERS.md arXiv 2401.16677): a caller can commit / apply the
    optimizer on bucket i while buckets i+1.. are still reducing.
    All results are async jax arrays; nothing here blocks.

    ``residuals``: per-leaf stacked error-feedback residuals aligned
    with ``leaves`` (entries may be None → zeros). Residuals engage
    only on buckets whose wire resolves to int8; other buckets yield
    ``None`` and the caller keeps its residuals untouched.

    ``topology``: a hierarchical :class:`Topology` routes sum/mean
    buckets through the 3-leg decomposition (inner reduce-scatter,
    outer exchange of ``1/n_inner`` of the bytes, inner allgather) —
    ``axis`` must be the composite ``("inner", "outer")`` tuple on the
    topology's mesh; max/min buckets fall back to the flat program
    over the same composite axis (same numerics, no decomposition).
    ``outer_residuals``: mutable per-bucket dict of outer-leg EF
    flats, read for the seed and updated in place per dispatch.
    """
    if op not in _REDUCERS:
        raise ValueError(f"bucketed_all_reduce: op must be one of "
                         f"{_REDUCERS}")
    if compress not in (None, "bf16", "int8"):
        raise ValueError(
            f"bucketed_all_reduce: unknown compression {compress!r}")
    leaves = [jnp.asarray(x) for x in leaves]
    n = axis_n(mesh, axis)
    buckets = plan_buckets(leaves, n, bucket_bytes)
    placed = _place_stacked(leaves, mesh, axis)
    for bi, b in enumerate(buckets):
        if topology is not None and op in ("sum", "mean"):
            wire_in, wire_out, qb_in, qb_out, restore = \
                _resolve_leg_wires(topology, b, op, compress,
                                   int8_min_bytes, q_block)
            ef_in = wire_in == "int8" and residuals is not None
            ef_out = (wire_out == "int8"
                      and outer_residuals is not None)
            fn = _hier_bucket_all_reduce_fn(
                mesh, op, tuple(s.shape for s in b.slots), b.dtype,
                b.pad, wire_in, wire_out, restore, qb_in, qb_out,
                ef_in, ef_out)
            args = [placed[s.index] for s in b.slots]
            if ef_in:
                args += _place_stacked(
                    [residuals[s.index]
                     if residuals[s.index] is not None
                     and tuple(residuals[s.index].shape)
                     == tuple(leaves[s.index].shape)
                     else jnp.zeros_like(leaves[s.index])
                     for s in b.slots], mesh, axis)
            if ef_out:
                args.append(_seed_outer_residual(
                    outer_residuals, bi,
                    (b.elems * int(topology.n_outer),), mesh))
            outs = fn(*args)
            _count_launch()
            _count_leg_bytes(topology, b, "allreduce",
                             wire_in, wire_out, qb_in, qb_out)
            if ef_out:
                outer_residuals[bi] = outs[-1]
                outs = outs[:-1]
            L = len(b.slots)
            yield b, list(outs[:L]), (list(outs[L:]) if ef_in
                                      else None)
            continue
        wire = _bucket_wire(b, op, compress, int8_min_bytes)
        ef = wire == "int8" and residuals is not None
        fn = _bucket_all_reduce_fn(
            mesh, axis, op, tuple(s.shape for s in b.slots), b.dtype,
            b.pad, wire, compress is not None, q_block, ef)
        args = [placed[s.index] for s in b.slots]
        if ef:
            args += _place_stacked(
                [residuals[s.index]
                 if residuals[s.index] is not None
                 and tuple(residuals[s.index].shape)
                 == tuple(leaves[s.index].shape)
                 else jnp.zeros_like(leaves[s.index])
                 for s in b.slots], mesh, axis)
        outs = fn(*args)
        _count_launch()
        L = len(b.slots)
        yield b, list(outs[:L]), (list(outs[L:]) if ef else None)


def bucketed_all_reduce(leaves, mesh: Mesh, axis: str = "data",
                        op: str = "sum", *,
                        bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                        compress: str | None = None,
                        int8_min_bytes: int = INT8_MIN_BUCKET_BYTES,
                        q_block: int | None = DEFAULT_QUANT_BLOCK,
                        residuals: list | None = None,
                        topology: Topology | None = None,
                        outer_residuals: dict | None = None):
    """Allreduce a flat list of stacked leaves through dtype buckets.

    Numerically identical to per-leaf :func:`all_reduce` on the exact
    path (same psum, different operand fusion); under ``compress`` the
    wire format resolves per bucket (:func:`_bucket_wire`) and int8
    payloads carry one scale per ``q_block`` elements. Buckets
    dispatch without any intervening sync, so every bucket's
    collective is in flight before the first result is consumed.

    Returns reduced leaves (shape ``rest``) in input order; when
    ``residuals`` is given, returns ``(reduced, new_residuals)`` where
    ``new_residuals[i]`` is the updated error-feedback residual for
    leaves that rode an int8 bucket and the input residual otherwise.
    """
    out: list = [None] * len(leaves)
    new_res = list(residuals) if residuals is not None else None
    for b, reduced, res in bucketed_all_reduce_stream(
            leaves, mesh, axis, op, bucket_bytes=bucket_bytes,
            compress=compress, int8_min_bytes=int8_min_bytes,
            q_block=q_block, residuals=residuals, topology=topology,
            outer_residuals=outer_residuals):
        for i, (s, r) in enumerate(zip(b.slots, reduced)):
            out[s.index] = r
            if res is not None:
                new_res[s.index] = res[i]
    return out if residuals is None else (out, new_res)


def tree_all_reduce(stacked_tree, mesh: Mesh, axis: str = "data",
                    op: str = "sum", *,
                    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                    compress: str | None = None,
                    int8_min_bytes: int = INT8_MIN_BUCKET_BYTES,
                    q_block: int | None = DEFAULT_QUANT_BLOCK):
    """Bucketed allreduce over a whole pytree of stacked contributions
    — the fused lowering of "push every leaf" (one collective per
    bucket, not per leaf). Returns the tree of reduced leaves."""
    leaves, treedef = jax.tree_util.tree_flatten(stacked_tree)
    reduced = bucketed_all_reduce(
        leaves, mesh, axis, op, bucket_bytes=bucket_bytes,
        compress=compress, int8_min_bytes=int8_min_bytes,
        q_block=q_block)
    return jax.tree_util.tree_unflatten(treedef, reduced)


@dataclasses.dataclass
class ScatteredTree:
    """Result of :func:`tree_reduce_scatter`: per-bucket flat shards.

    Each bucket's reduction lives as a flat ``(elems,)`` array sharded
    over ``axis`` — each device owns ``elems/n`` contiguous elements
    (the ZeRO/FSDP resident form). :meth:`gather` reassembles the full
    tree via one allgather-reshard per bucket.

    This flat-bucket layout is the repo's ONE resident sharded form
    (ISSUE 17): grads here, Adam moments and ZeRO-3 param shards in
    ``zero.ZeroState`` all live as ``(elems,)`` flats over the same
    ``ShardPlan`` slot space. Because slot offsets are replica-count
    independent (only tail pads depend on n), live resharding across
    a survivor set is strip-pad / re-pad / re-place — no layout
    translation (``ZeroState.reshard``).
    """

    treedef: object
    buckets: list          # [(Bucket, flat jax.Array sharded P(axis))]
    mesh: Mesh
    axis: str
    n_leaves: int

    def gather(self):
        """Allgather every bucket and unpack back to the pytree —
        together with the scatter this is the bandwidth-optimal
        allreduce decomposition."""
        flats = jax.device_put(
            [a for _, a in self.buckets],
            [NamedSharding(self.mesh, P())] * len(self.buckets))
        leaves: list = [None] * self.n_leaves
        for (b, _), flat in zip(self.buckets, flats):
            for s, r in zip(b.slots, _unpack(flat, b.slots)):
                leaves[s.index] = r
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


def tree_reduce_scatter(stacked_tree, mesh: Mesh, axis: str = "data",
                        op: str = "sum", *,
                        bucket_bytes: int = DEFAULT_BUCKET_BYTES,
                        compress: str | None = None,
                        int8_min_bytes: int = INT8_MIN_BUCKET_BYTES,
                        q_block: int | None = DEFAULT_QUANT_BLOCK
                        ) -> ScatteredTree:
    """Bucketed reduce-scatter over a pytree: half the allreduce's ICI
    bytes, each device left holding one flat shard per bucket. Pad to
    a multiple of the axis size makes every bucket eligible — no
    per-leaf ``rest[0] % n`` lottery."""
    if op not in ("sum", "mean"):
        raise ValueError(
            f"tree_reduce_scatter: op must be 'sum' or 'mean', got "
            f"{op!r}")
    if compress not in (None, "bf16", "int8"):
        raise ValueError(
            f"tree_reduce_scatter: unknown compression {compress!r}")
    leaves, treedef = jax.tree_util.tree_flatten(stacked_tree)
    leaves = [jnp.asarray(x) for x in leaves]
    n = axis_n(mesh, axis)
    buckets = plan_buckets(leaves, n, bucket_bytes)
    placed = _place_stacked(leaves, mesh, axis)
    shards = []
    for b in buckets:
        fn = _bucket_reduce_scatter_fn(
            mesh, axis, op, tuple(s.shape for s in b.slots), b.dtype,
            b.pad, _bucket_wire(b, op, compress, int8_min_bytes),
            compress is not None, q_block)
        shards.append((b, fn(*[placed[s.index] for s in b.slots])))
        _count_launch()
    return ScatteredTree(treedef, shards, mesh, axis, len(leaves))


# ------------------------------------------------ host-side wire codec
#
# The same block-scaled int8 + error-feedback wire, applied per leaf on
# the HOST side — for gradients that ride a TCP RPC instead of an ICI
# collective (the async param-server push, train/param_server.py).
# Format is codec-marshallable (dicts + arrays), ~4× fewer wire bytes.

_Q8_KEY = "__ptype_q8__"


def quantize_leaf(x, q_block: int | None = DEFAULT_QUANT_BLOCK,
                  residual=None, *, want_residual: bool = True):
    """Block-scaled int8 encoding of one array (+ optional EF residual
    added in before quantizing). Returns ``(wire_dict, new_residual)``;
    non-float arrays pass through unquantized (``new_residual=None``).
    ``want_residual=False`` skips the dequantize+subtract entirely —
    a feedback-disarmed caller must not pay for a residual it
    discards."""
    x = jnp.asarray(x)
    if not jnp.issubdtype(x.dtype, jnp.floating) or x.size == 0:
        return {_Q8_KEY: 0, "raw": x}, None
    flat = x.astype(jnp.float32).reshape(1, -1)
    if residual is not None and residual.size == x.size:
        flat = flat + residual.reshape(1, -1).astype(jnp.float32)
    q, scale = _q_int8_blockwise(flat, q_block)
    new_res = None
    if want_residual:
        new_res = (flat - _dq_int8_blockwise(q, scale, flat.shape[1])
                   ).reshape(x.shape).astype(x.dtype)
    return {_Q8_KEY: 1, "q": q[0], "s": scale[0],
            "shape": list(x.shape), "dtype": str(x.dtype)}, new_res


def dequantize_leaf(wire: dict):
    """Inverse of :func:`quantize_leaf`."""
    if not wire.get(_Q8_KEY):
        return wire["raw"]
    n = 1
    for d in wire["shape"]:
        n *= int(d)
    out = _dq_int8_blockwise(jnp.asarray(wire["q"])[None],
                             jnp.asarray(wire["s"])[None], n)
    return out.reshape(wire["shape"]).astype(jnp.dtype(wire["dtype"]))


def quantize_tree(tree, q_block: int | None = DEFAULT_QUANT_BLOCK,
                  residuals: list | None = None, *,
                  want_residuals: bool = True):
    """Encode a pytree for the RPC wire: ``({"__ptype_q8_tree__":
    [leaf wires in tree_flatten order]}, new_residuals)``. The
    receiver reassembles with its own treedef
    (:func:`dequantize_tree`) — both ends of a param-server push
    already share the parameter structure."""
    leaves = jax.tree_util.tree_leaves(tree)
    wires, new_res = [], []
    for i, leaf in enumerate(leaves):
        r = residuals[i] if residuals is not None else None
        w, nr = quantize_leaf(leaf, q_block, r,
                              want_residual=want_residuals)
        wires.append(w)
        new_res.append(nr)
    return {"__ptype_q8_tree__": wires}, new_res


def is_quantized_tree(obj) -> bool:
    return isinstance(obj, dict) and "__ptype_q8_tree__" in obj


def dequantize_tree(obj, treedef):
    """Decode :func:`quantize_tree` output back into ``treedef``."""
    leaves = [dequantize_leaf(w) for w in obj["__ptype_q8_tree__"]]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def measure_allreduce_gbps(mesh: Mesh, axis: str = "data",
                           mbytes: int = 64, iters: int = 10) -> float:
    """Measured algorithmic allreduce bandwidth (GB/s) over ``axis`` — the
    BASELINE.md "Store push/pull collective bandwidth" metric."""
    import time

    n = axis_n(mesh, axis)
    elems = mbytes * 1024 * 1024 // 4
    # Pre-place the input in the collective's layout so the timed loop
    # measures only the compiled allreduce, not a per-iteration reshard.
    x = jax.device_put(
        jnp.ones((n, elems), jnp.float32),
        NamedSharding(mesh, P(axis, None)),
    )
    fn = _all_reduce_fn(mesh, axis, 2, "sum")
    fn(x).block_until_ready()  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    # Ring allreduce moves 2*(n-1)/n of the buffer per device.
    bytes_moved = 2 * (n - 1) / n * elems * 4
    return bytes_moved / dt / 1e9


def measure_wire_gbps(mesh: Mesh, axis: str = "data", mbytes: int = 32,
                      iters: int = 5,
                      blocks: tuple = (256, 512, 1024)) -> dict:
    """Algorithmic bandwidth of one bucketed allreduce under each wire
    format — fp32 (exact) vs PR 1's per-chunk-scale int8 vs the
    block-scaled int8 wire at several block sizes. The bench.py
    ``store_wire_gbps`` probe and the PERF.md block-size sweep.

    GB/s is app-level (f32 payload bytes reduced per second, ring
    convention 2(n-1)/n), so a wire that spends less time on the same
    payload scores higher whatever bytes it moved. ``wire_bytes_pct``
    is the analytic wire footprint of each int8 format vs fp32."""
    import time

    n = axis_n(mesh, axis)
    elems = mbytes * 1024 * 1024 // 4
    leaf = jax.device_put(
        jnp.ones((n, elems), jnp.float32) * 0.5,
        NamedSharding(mesh, P(axis, None)))
    app_bytes = 2 * (n - 1) / n * elems * 4

    def timed(compress, q_block):
        def run():
            return bucketed_all_reduce(
                [leaf], mesh, axis, "sum", compress=compress,
                int8_min_bytes=0, q_block=q_block)[0]

        run().block_until_ready()  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = run()
        out.block_until_ready()
        return round(app_bytes / ((time.perf_counter() - t0) / iters)
                     / 1e9, 3)

    def wire_pct(q_block):
        if q_block is None:
            q_block = elems // n
        return round(100.0 * (elems + elems / q_block * 4)
                     / (elems * 4), 2)

    return {
        "payload_mb": mbytes,
        "fp32_gbps": timed(None, None),
        "int8_chunk_gbps": timed("int8", None),
        "int8_chunk_wire_pct": wire_pct(None),
        "int8_block_gbps": {str(b): timed("int8", b) for b in blocks},
        "int8_block_wire_pct": {str(b): wire_pct(b) for b in blocks},
    }


def measure_hier_allreduce(topology: Topology | None = None,
                           mbytes: int = 16, iters: int = 5) -> dict:
    """Hierarchical vs flat bucketed allreduce over the SAME composite
    mesh — the ``make hier-bench`` probe (ISSUE 18).

    The flat baseline is the one-launch bucketed program over the
    composite ``("inner", "outer")`` axis; the hierarchical program is
    the 3-leg decomposition (inner reduce-scatter, outer exchange of
    ``1/n_inner`` of the bytes, inner allgather), both at the exact
    wire. On the virtual host mesh every hop is host memory, so the
    measured step times price launch overhead only; the wire
    acceptance is the slow-leg byte counter (``hier_slow_leg_bytes``
    <= ``flat_outer_bytes / n_inner``) and the topology's per-leg
    bandwidth model prices the same two programs on the emulated
    ICI/DCN asymmetry (``model_*`` fields)."""
    import time

    from ptype_tpu.metrics import metrics

    if topology is None:
        n = len(jax.devices())
        no = 2 if n % 2 == 0 and n >= 4 else 1
        topology = Topology.emulated_host(no, max(n // no, 1))
    topo = topology
    n = topo.n
    elems = mbytes * 1024 * 1024 // 4
    payload = elems * 4
    mesh, ax = topo.mesh(), topo.flat_axis
    leaf = jax.device_put(jnp.ones((n, elems), jnp.float32) * 0.5,
                          NamedSharding(mesh, P(ax, None)))

    def timed(run):
        run()[0].block_until_ready()  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = run()
        out[0].block_until_ready()
        return round((time.perf_counter() - t0) / iters * 1e3, 3)

    flat_ms = timed(
        lambda: bucketed_all_reduce([leaf], mesh, ax, "sum"))

    def snap():
        c = metrics.snapshot()["counters"]
        keys = ("leg_bytes.inner", "leg_bytes.outer",
                "leg_bytes.flat_outer", "hier_launches")
        return {k: c.get(f"collectives.{k}", 0) for k in keys}

    base = snap()
    hier_ms = timed(
        lambda: bucketed_all_reduce([leaf], mesh, ax, "sum",
                                    topology=topo))
    d = {k: v - base[k] for k, v in snap().items()}
    launches = max(int(d["hier_launches"]), 1)
    slow = d["leg_bytes.outer"] / launches
    flat_outer = d["leg_bytes.flat_outer"] / launches
    model_flat = topo.flat_allreduce_ms(payload)
    model_hier = topo.hier_allreduce_ms(payload)
    return {
        "geometry": topo.describe(),
        "payload_mb": mbytes,
        "flat_step_ms": flat_ms,
        "hier_step_ms": hier_ms,
        "hier_slow_leg_bytes": int(slow),
        "hier_inner_leg_bytes": int(d["leg_bytes.inner"] / launches),
        "flat_outer_bytes": int(flat_outer),
        "slow_leg_pct": (round(100.0 * slow / flat_outer, 2)
                         if flat_outer else None),
        "model_flat_ms": round(model_flat, 3),
        "model_hier_ms": round(model_hier, 3),
        "model_speedup": (round(model_flat / model_hier, 2)
                          if model_hier else None),
    }
