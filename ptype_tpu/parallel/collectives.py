"""Compiled XLA collectives over mesh axes — the ICI data plane.

The reference's data plane was gob-encoded ``net/rpc`` over TCP
(cluster/rpc.go:277); here the equivalent primitive set is XLA collectives
compiled over ICI (SURVEY.md §2 "Distributed communication backend").
These wrappers give the *eager* entry points the TensorStore and benches
use; inside a jit'ed train step you use ``jax.lax`` collectives (under
``shard_map``) or let GSPMD insert them from sharding annotations.

Conventions: the "stacked" layout carries one leading contribution axis of
size ``mesh.shape[axis]``, sharded over ``axis`` — the eager analog of
per-worker values in a multi-controller program.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

_REDUCERS = ("sum", "mean", "max", "min")


def _rest(ndim: int) -> tuple[None, ...]:
    return (None,) * (ndim - 1)


@functools.lru_cache(maxsize=256)
def _all_reduce_fn(mesh: Mesh, axis: str, ndim: int, op: str):
    in_spec = P(axis, *_rest(ndim))
    out_spec = P(*_rest(ndim))

    def f(local):
        x = jnp.squeeze(local, axis=0)
        if op == "sum":
            return lax.psum(x, axis)
        if op == "mean":
            return lax.pmean(x, axis)
        if op == "max":
            return lax.pmax(x, axis)
        return lax.pmin(x, axis)

    return jax.jit(
        shard_map(f, mesh=mesh, in_specs=in_spec, out_specs=out_spec)
    )


def all_reduce(stacked: jax.Array, mesh: Mesh, axis: str = "data",
               op: str = "sum") -> jax.Array:
    """Reduce per-worker contributions; result replicated over ``axis``.

    ``stacked``: shape ``(mesh.shape[axis], *rest)``, sharded on dim 0.
    Returns shape ``rest`` with every device holding the reduction — the
    Store push lowering (ref Put store.go:56-62 → psum).
    """
    if op not in _REDUCERS:
        raise ValueError(f"all_reduce: op must be one of {_REDUCERS}")
    n = int(mesh.shape[axis])
    if stacked.shape[0] != n:
        raise ValueError(
            f"all_reduce: leading dim {stacked.shape[0]} != axis size {n}"
        )
    stacked = jax.device_put(stacked, NamedSharding(mesh, P(axis, *_rest(stacked.ndim))))
    return _all_reduce_fn(mesh, axis, stacked.ndim, op)(stacked)


@functools.lru_cache(maxsize=256)
def _all_gather_fn(mesh: Mesh, axis: str, ndim: int):
    spec = P(axis, *_rest(ndim))

    def f(local):
        return lax.all_gather(jnp.squeeze(local, axis=0), axis)

    # all_gather's output is replicated by construction, but the varying-
    # manual-axes check cannot infer that — disable it for this wrapper.
    return jax.jit(
        shard_map(f, mesh=mesh, in_specs=spec,
                  out_specs=P(*_rest(ndim + 1)), check_vma=False)
    )


def all_gather(stacked: jax.Array, mesh: Mesh, axis: str = "data") -> jax.Array:
    """Gather per-worker contributions to every device, replicated.

    ``(n, *rest)`` sharded on dim 0 → ``(n, *rest)`` replicated — the Store
    pull lowering (ref Get store.go:38-53 → allgather).
    """
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P(axis, *_rest(stacked.ndim)))
    )
    return _all_gather_fn(mesh, axis, stacked.ndim)(stacked)


@functools.lru_cache(maxsize=256)
def _reduce_scatter_fn(mesh: Mesh, axis: str, ndim: int, op: str):
    in_spec = P(axis, *_rest(ndim))
    # Output keeps rank ndim-1; dim 0 of the payload is scattered.
    out_spec = P(axis, *_rest(ndim - 1))

    def f(local):
        x = jnp.squeeze(local, axis=0)
        n = lax.axis_size(axis)
        red = lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
        if op == "mean":
            red = red / n
        return red

    return jax.jit(shard_map(f, mesh=mesh, in_specs=in_spec, out_specs=out_spec))


def reduce_scatter(stacked: jax.Array, mesh: Mesh, axis: str = "data",
                   op: str = "sum") -> jax.Array:
    """Reduce contributions, leaving each device one shard of the result.

    ``(n, *payload)`` with ``payload[0] % n == 0`` → ``payload`` sharded on
    dim 0 over ``axis``. Half the ICI bytes of an all_reduce when the
    consumer is itself sharded (ZeRO/FSDP-style grad reduction).
    """
    if op not in ("sum", "mean"):
        raise ValueError(
            f"reduce_scatter: op must be 'sum' or 'mean', got {op!r}"
        )
    n = int(mesh.shape[axis])
    if stacked.ndim < 2 or stacked.shape[1] % n != 0:
        raise ValueError(
            f"reduce_scatter: payload dim 0 ({stacked.shape[1:]}) must "
            f"divide by axis size {n}"
        )
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P(axis, *_rest(stacked.ndim)))
    )
    return _reduce_scatter_fn(mesh, axis, stacked.ndim, op)(stacked)


@functools.lru_cache(maxsize=256)
def _ring_shift_fn(mesh: Mesh, axis: str, ndim: int, shift: int):
    spec = P(axis, *_rest(ndim))

    def f(local):
        n = lax.axis_size(axis)
        perm = [(i, (i + shift) % n) for i in range(n)]
        return lax.ppermute(local, axis, perm)

    return jax.jit(shard_map(f, mesh=mesh, in_specs=spec, out_specs=spec))


def ring_shift(stacked: jax.Array, mesh: Mesh, axis: str = "data",
               shift: int = 1) -> jax.Array:
    """Rotate shards around the ``axis`` ring by ``shift`` (ppermute) —
    the building block of ring attention (SURVEY.md §5 long-context)."""
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P(axis, *_rest(stacked.ndim)))
    )
    return _ring_shift_fn(mesh, axis, stacked.ndim, shift)(stacked)


@functools.lru_cache(maxsize=256)
def _all_to_all_fn(mesh: Mesh, axis: str, ndim: int):
    spec = P(axis, *_rest(ndim))

    def f(local):
        # local: (1, n*chunk, *rest) → exchange chunks around the axis.
        x = jnp.squeeze(local, axis=0)
        out = lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=True)
        return out[None]

    return jax.jit(shard_map(f, mesh=mesh, in_specs=spec, out_specs=spec))


def all_to_all(stacked: jax.Array, mesh: Mesh, axis: str = "data") -> jax.Array:
    """Transpose shard ownership: device i's chunk j goes to device j —
    the EP/Ulysses exchange. ``(n, n*chunk, *rest)`` sharded on dim 0."""
    n = int(mesh.shape[axis])
    if stacked.ndim < 2 or stacked.shape[1] % n != 0:
        raise ValueError(
            f"all_to_all: payload dim 0 must divide by axis size {n}"
        )
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P(axis, *_rest(stacked.ndim)))
    )
    return _all_to_all_fn(mesh, axis, stacked.ndim)(stacked)


def _q_int8_chunks(x: jax.Array):
    """Int8-quantize with one absmax scale per dim-0 chunk.
    ``x: (m, ...)`` → ``(int8 like x, f32 scales (m,))``. Deterministic
    round-to-nearest — collective results must be reproducible across
    reruns for the numerics test tier."""
    amax = jnp.max(jnp.abs(x).reshape(x.shape[0], -1), axis=1)
    scale = jnp.where(amax == 0.0, 1.0, amax / 127.0).astype(jnp.float32)
    sb = scale.reshape((x.shape[0],) + (1,) * (x.ndim - 1))
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / sb),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _int8_phase1(x, axis: str, op: str):
    """The int8 reduce-scatter leg, shared by the quantized allreduce
    and the standalone quantized reduce_scatter (one implementation so
    numerics fixes can't drift between them): slice my contribution
    into n chunks, quantize each with one absmax scale, all_to_all so
    device j collects everyone's chunk j, dequantize and reduce.
    Returns this device's reduced f32 chunk ``(rest[0]/n, *tail)``."""
    n = lax.axis_size(axis)
    c = x.shape[0] // n
    chunks = x.reshape((n, c) + x.shape[1:])
    q, scale = _q_int8_chunks(chunks)
    q = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    scale = lax.all_to_all(scale, axis, split_axis=0, concat_axis=0,
                           tiled=True)
    q = q.reshape((n, c) + x.shape[1:])
    red = jnp.sum(
        q.astype(jnp.float32) * scale.reshape((n,) + (1,) * x.ndim),
        axis=0)
    if op == "mean":
        red = red / n
    return red


@functools.lru_cache(maxsize=256)
def _quantized_all_reduce_fn(mesh: Mesh, axis: str, ndim: int, op: str):
    in_spec = P(axis, *_rest(ndim))
    out_spec = P(*_rest(ndim))

    def f(local):
        x = jnp.squeeze(local, axis=0)  # my contribution, shape `rest`
        n = lax.axis_size(axis)
        red = _int8_phase1(x, axis, op)
        # Phase 2 (all_gather leg): re-quantize my reduced chunk with
        # one scale, gather, dequantize — every device reassembles the
        # full reduced tensor.
        q2, s2 = _q_int8_chunks(red[None])  # one chunk → one scale
        qg = lax.all_gather(jnp.squeeze(q2, 0), axis)   # (n, c, *tail)
        sg = lax.all_gather(s2[0], axis)                # (n,)
        out = qg.astype(jnp.float32) * sg.reshape(
            (n,) + (1,) * x.ndim)
        return out.reshape(x.shape)

    return jax.jit(
        shard_map(f, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
                  check_vma=False)
    )


@functools.lru_cache(maxsize=256)
def _quantized_reduce_scatter_fn(mesh: Mesh, axis: str, ndim: int,
                                 op: str):
    in_spec = P(axis, *_rest(ndim))
    out_spec = P(axis, *_rest(ndim - 1))

    def f(local):
        return _int8_phase1(jnp.squeeze(local, axis=0), axis, op)

    return jax.jit(shard_map(f, mesh=mesh, in_specs=in_spec,
                             out_specs=out_spec))


def quantized_reduce_scatter(stacked: jax.Array, mesh: Mesh,
                             axis: str = "data",
                             op: str = "sum") -> jax.Array:
    """Phase 1 of :func:`quantized_all_reduce` alone: int8-quantized
    all_to_all + local dequant-reduce — each device keeps ONE f32
    shard of the reduced tensor (the bandwidth-optimal int8 grad
    reduction for consumers that are themselves sharded, ZeRO/FSDP
    style). Same shape contract and error bound as the allreduce's
    first phase (one round-to-nearest quantization)."""
    n = int(mesh.shape[axis])
    if not quantized_all_reduce_eligible(stacked.shape, n, op):
        raise ValueError(
            f"quantized_reduce_scatter: need op in sum/mean (got "
            f"{op!r}), leading dim == axis size {n} (got "
            f"{stacked.shape[0]}), and payload dim 0 to divide by {n} "
            f"(got {stacked.shape[1:]})")
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P(axis, *_rest(stacked.ndim))))
    return _quantized_reduce_scatter_fn(mesh, axis, stacked.ndim,
                                        op)(stacked)


def quantized_all_reduce_eligible(shape: tuple, n: int,
                                  op: str) -> bool:
    """Whether a stacked ``(n, *rest)`` payload can take the int8 path
    — the single source of its constraints (callers like TensorStore
    route ineligible leaves to the exact allreduce)."""
    return (op in ("sum", "mean") and len(shape) >= 2
            and shape[0] == n and shape[1] % n == 0)


def quantized_all_reduce(stacked: jax.Array, mesh: Mesh,
                         axis: str = "data",
                         op: str = "sum") -> jax.Array:
    """Int8-quantized allreduce — the EQuARX pattern (PAPERS.md): both
    wire phases of the bandwidth-optimal allreduce decomposition
    (all_to_all reduce-scatter, then all_gather) carry int8 payloads
    with f32 blockwise absmax scales, ≈4× fewer ICI bytes than f32 at
    a bounded relative error (two round-to-nearest quantizations of
    ≤ absmax/254 each). Lossy: for gradients, not parameters.

    ``stacked``: ``(axis_size, *rest)`` with ``rest[0] % axis_size
    == 0``; returns ``rest`` in f32, replicated.
    """
    n = int(mesh.shape[axis])
    if not quantized_all_reduce_eligible(stacked.shape, n, op):
        raise ValueError(
            f"quantized_all_reduce: need op in sum/mean (got {op!r}), "
            f"leading dim == axis size {n} (got {stacked.shape[0]}), "
            f"and payload dim 0 to divide by {n} "
            f"(got {stacked.shape[1:]})")
    stacked = jax.device_put(
        stacked, NamedSharding(mesh, P(axis, *_rest(stacked.ndim))))
    return _quantized_all_reduce_fn(mesh, axis, stacked.ndim, op)(stacked)


def broadcast(value: jax.Array, mesh: Mesh) -> jax.Array:
    """Replicate a host/single-device value across the whole mesh."""
    return jax.device_put(value, NamedSharding(mesh, P()))


def measure_allreduce_gbps(mesh: Mesh, axis: str = "data",
                           mbytes: int = 64, iters: int = 10) -> float:
    """Measured algorithmic allreduce bandwidth (GB/s) over ``axis`` — the
    BASELINE.md "Store push/pull collective bandwidth" metric."""
    import time

    n = int(mesh.shape[axis])
    elems = mbytes * 1024 * 1024 // 4
    # Pre-place the input in the collective's layout so the timed loop
    # measures only the compiled allreduce, not a per-iteration reshard.
    x = jax.device_put(
        jnp.ones((n, elems), jnp.float32),
        NamedSharding(mesh, P(axis, None)),
    )
    fn = _all_reduce_fn(mesh, axis, 2, "sum")
    fn(x).block_until_ready()  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    # Ring allreduce moves 2*(n-1)/n of the buffer per device.
    bytes_moved = 2 * (n - 1) / n * elems * 4
    return bytes_moved / dt / 1e9
