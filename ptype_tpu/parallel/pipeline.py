"""Pipeline parallelism over the ``stage`` mesh axis — compiled SPMD.

The reference's closest analog was actor-per-service topology
(cluster/registry.go:17-21; SURVEY.md §2 parallelism table "PP"). The
TPU-native lowering is NOT per-layer RPC: all stages run ONE compiled
SPMD program; microbatches flow around the ``stage`` ring via
``lax.ppermute`` inside a ``lax.scan`` over pipeline ticks (GPipe-style
schedule, bubble = (S-1)/(M+S-1)). Autodiff through the scan+ppermute
gives the reverse pipeline for free — ppermute's transpose is the
reverse rotation, so one ``jax.grad`` yields forward AND backward
pipelining with no hand-written schedule.

Layer split: the transformer's stacked blocks (leading ``n_layers`` dim,
models/transformer.py init_params) reshape to ``(S, L/S, ...)`` and
shard dim 0 over ``stage`` — each device holds only its stage's layers,
the actor-per-layer memory model without the RPC hops.

(The registry-driven actor pipeline — PID→stage over real RPC — lives in
ptype_tpu/train/actor_pipeline.py; this module is the throughput path.)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ptype_tpu.errors import ClusterError


def split_stages(blocks: dict, n_stages: int) -> dict:
    """Reshape stacked block params (L, ...) → (S, L/S, ...)."""

    def resh(x):
        L = x.shape[0]
        if L % n_stages:
            raise ClusterError(
                f"pipeline: {L} layers not divisible into {n_stages} stages"
            )
        return x.reshape(n_stages, L // n_stages, *x.shape[1:])

    return jax.tree.map(resh, blocks)


def merge_stages(blocks: dict) -> dict:
    """Inverse of :func:`split_stages`: (S, L/S, ...) → (L, ...)."""
    return jax.tree.map(
        lambda x: x.reshape(x.shape[0] * x.shape[1], *x.shape[2:]), blocks
    )


def _spmd_pipeline(stage_fn, stage_params, x_mb, *, axis: str,
                   n_stages: int, n_microbatches: int):
    """Run the pipeline on one device (inside shard_map over ``axis``).

    ``stage_params``: (1, L/S, ...) — this stage's layers (leading stage
    shard dim of size 1). ``x_mb``: (M, mb, ...) microbatched activations
    (replicated over the stage axis). Returns (M, mb, ...) outputs of the
    LAST stage (replicated via collective broadcast at the end).
    """
    stage = lax.axis_index(axis)
    S, M = n_stages, n_microbatches
    params = jax.tree.map(lambda p: jnp.squeeze(p, axis=0), stage_params)
    mb_shape = x_mb.shape[1:]

    state = jnp.zeros(mb_shape, x_mb.dtype)  # activation in flight
    outputs = jnp.zeros_like(x_mb)
    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 ingests microbatch t (while t < M); other stages keep
        # the activation that just arrived from their predecessor.
        inject = x_mb[jnp.minimum(t, M - 1) % M]
        state = jnp.where(stage == 0, jnp.where(t < M, inject, state),
                          state)
        state = stage_fn(params, state)
        # The LAST stage has just finished microbatch t-(S-1).
        out_t = t - (S - 1)
        is_out = (stage == S - 1) & (out_t >= 0)
        outputs = jnp.where(
            is_out,
            jax.lax.dynamic_update_index_in_dim(
                outputs, state.astype(outputs.dtype),
                jnp.maximum(out_t, 0) % M, 0),
            outputs,
        )
        state = lax.ppermute(state, axis, perm)
        return (state, outputs), None

    (state, outputs), _ = lax.scan(
        tick, (state, outputs), jnp.arange(M + S - 1)
    )
    # Outputs live on the last stage only; broadcast around the ring so
    # every stage returns the same (replicated out_spec).
    outputs = lax.psum(
        jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)), axis
    )
    return outputs


def pipeline_apply(stage_fn, stage_params, x, mesh: Mesh,
                   n_microbatches: int, axis: str = "stage"):
    """Apply a stage-sharded layer stack to ``x`` through the pipeline.

    ``stage_fn(params_one_stage, x_mb) -> y_mb`` runs this stage's layer
    chunk on one microbatch. ``stage_params`` leaves carry a leading
    ``n_stages`` dim (from :func:`split_stages`), sharded over ``axis``.
    ``x``: (B, ...) with B divisible by ``n_microbatches``.
    """
    S = int(mesh.shape[axis])
    B = x.shape[0]
    if B % n_microbatches:
        raise ClusterError(
            f"pipeline: batch {B} not divisible into {n_microbatches} "
            "microbatches"
        )
    x_mb = x.reshape(n_microbatches, B // n_microbatches, *x.shape[1:])

    param_specs = jax.tree.map(
        lambda p: P(axis, *(None,) * (p.ndim - 1)), stage_params
    )
    fn = shard_map(
        partial(_spmd_pipeline, stage_fn, axis=axis, n_stages=S,
                n_microbatches=n_microbatches),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )
    y_mb = fn(stage_params, x_mb)
    return y_mb.reshape(B, *y_mb.shape[2:])


# ------------------------------------------------- transformer integration


def transformer_pipeline_forward(params: dict, tokens: jax.Array, cfg,
                                 mesh: Mesh, n_microbatches: int,
                                 axis: str = "stage") -> jax.Array:
    """models/transformer.forward with the block stack pipelined.

    Embedding and the LM head stay outside the pipeline (they are one
    matmul each); the L blocks split into ``stage``-many chunks. Same
    logits as the dense forward, modulo bf16 accumulation order.
    """
    from ptype_tpu.models import transformer as tfm

    if cfg.n_experts:
        # The stage ring carries activations only; threading the MoE
        # router aux loss through it is not implemented — refusing beats
        # silently optimizing a different objective than the dense path.
        raise ClusterError(
            "pipeline parallelism does not support MoE configs yet "
            "(router aux loss would be dropped); use dp/fsdp/tp/ep"
        )
    S = int(mesh.shape[axis])
    B, T = tokens.shape
    dt = cfg.dtype
    x = params["embed"][tokens].astype(dt)
    sin, cos = tfm.rope_tables(cfg, T)
    stage_blocks = split_stages(params["blocks"], S)

    def stage_fn(blocks, x_mb):
        def body(x, layer):
            x, _aux = tfm._block(x, layer, sin, cos, cfg, tfm._attention)
            return x, None

        if cfg.remat:
            body = jax.checkpoint(body)
        x_mb, _ = lax.scan(body, x_mb, blocks)
        return x_mb

    x = pipeline_apply(stage_fn, stage_blocks, x, mesh, n_microbatches,
                       axis)
    x = tfm.rms_norm(x, params["final_norm"])
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    return jnp.einsum("bsd,dv->bsv", x.astype(jnp.float32),
                      head.astype(jnp.float32))


def pipeline_state_shardings(params_like, mesh: Mesh, optimizer,
                             axis: str = "stage"):
    """NamedSharding pytree for a pipelined TrainState: block leaves
    shard their leading layer dim over ``axis`` (L = S·L/S, so the
    per-stage split is a local reshape), everything else replicated;
    optax moments mirror the params."""
    from ptype_tpu.train.trainer import TrainState

    def param_sh(path, leaf):
        top = getattr(path[0], "key", None) if path else None
        if top == "blocks":
            return NamedSharding(mesh, P(axis, *(None,) * (leaf.ndim - 1)))
        return NamedSharding(mesh, P())

    params_shape = jax.eval_shape(lambda: params_like) \
        if not hasattr(jax.tree.leaves(params_like)[0], "shape") \
        else params_like
    p_sh = jax.tree_util.tree_map_with_path(param_sh, params_shape)
    opt_shape = jax.eval_shape(optimizer.init, params_shape)

    from ptype_tpu.train.trainer import opt_state_shardings

    repl = NamedSharding(mesh, P())
    o_sh = opt_state_shardings(opt_shape, params_shape, p_sh, repl)
    return TrainState(p_sh, o_sh, repl)


def make_pipeline_train_step(cfg, mesh: Mesh, n_microbatches: int,
                             optimizer=None, axis: str = "stage",
                             state_shardings=None):
    """(state, batch) → (state, metrics) with the block stack pipelined.

    State layout matches train/trainer.py's TrainState, so checkpoints
    interchange between pipelined and dense training. Pass
    ``state_shardings`` (from :func:`pipeline_state_shardings`) to pin
    each stage's layers — and their Adam moments — to that stage's
    devices; without it the state is replicated (fine for tests, wrong
    for models sized to per-stage memory).
    """
    import optax

    from ptype_tpu.models import transformer as tfm
    from ptype_tpu.train.trainer import TrainState, default_optimizer

    optimizer = optimizer or default_optimizer()

    def loss_fn(p, batch):
        logits = transformer_pipeline_forward(
            p, batch["tokens"], cfg, mesh, n_microbatches, axis
        )
        return tfm.nll_from_logits(logits, batch)

    def step(state: TrainState, batch: dict):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new = TrainState(new_params, opt_state, state.step + 1)
        return new, {"loss": loss, "step": new.step}

    kw = {}
    if state_shardings is not None:
        kw = {"in_shardings": (state_shardings,
                               NamedSharding(mesh, P())),
              "out_shardings": (state_shardings,
                                {"loss": NamedSharding(mesh, P()),
                                 "step": NamedSharding(mesh, P())})}
    return jax.jit(step, donate_argnums=(0,), **kw)
