"""Pipeline parallelism over the ``stage`` mesh axis — compiled SPMD.

The reference's closest analog was actor-per-service topology
(cluster/registry.go:17-21; SURVEY.md §2 parallelism table "PP"). The
TPU-native lowering is NOT per-layer RPC: all stages run ONE compiled
SPMD program; microbatches flow around the ``stage`` ring via
``lax.ppermute`` inside a ``lax.scan`` over pipeline ticks (GPipe-style
schedule, bubble = (S-1)/(M+S-1)). Autodiff through the scan+ppermute
gives the reverse pipeline for free — ppermute's transpose is the
reverse rotation, so one ``jax.grad`` yields forward AND backward
pipelining with no hand-written schedule.

Layer split: the transformer's stacked blocks (leading ``n_layers`` dim,
models/transformer.py init_params) reshape to ``(S, L/S, ...)`` and
shard dim 0 over ``stage`` — each device holds only its stage's layers,
the actor-per-layer memory model without the RPC hops.

(The registry-driven actor pipeline — PID→stage over real RPC — lives in
ptype_tpu/train/actor_pipeline.py; this module is the throughput path.)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ptype_tpu.compat import shard_map
from ptype_tpu.errors import ClusterError


def split_stages(blocks: dict, n_stages: int) -> dict:
    """Reshape stacked block params (L, ...) → (S, L/S, ...)."""

    def resh(x):
        L = x.shape[0]
        if L % n_stages:
            raise ClusterError(
                f"pipeline: {L} layers not divisible into {n_stages} stages"
            )
        return x.reshape(n_stages, L // n_stages, *x.shape[1:])

    return jax.tree.map(resh, blocks)


def merge_stages(blocks: dict) -> dict:
    """Inverse of :func:`split_stages`: (S, L/S, ...) → (L, ...)."""
    return jax.tree.map(
        lambda x: x.reshape(x.shape[0] * x.shape[1], *x.shape[2:]), blocks
    )


def _spmd_pipeline(stage_fn, stage_params, x_mb, *, axis: str,
                   n_stages: int, n_microbatches: int):
    """Run the pipeline on one device (inside shard_map over ``axis``).

    ``stage_params``: (1, L/S, ...) — this stage's layers (leading stage
    shard dim of size 1). ``x_mb``: (M, mb, ...) microbatched activations
    (replicated over the stage axis). Returns (M, mb, ...) outputs of the
    LAST stage (replicated via collective broadcast at the end).
    """
    stage = lax.axis_index(axis)
    S, M = n_stages, n_microbatches
    params = jax.tree.map(lambda p: jnp.squeeze(p, axis=0), stage_params)
    mb_shape = x_mb.shape[1:]

    state = jnp.zeros(mb_shape, x_mb.dtype)  # activation in flight
    outputs = jnp.zeros_like(x_mb)
    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        state, outputs = carry
        # Stage 0 ingests microbatch t (while t < M); other stages keep
        # the activation that just arrived from their predecessor.
        inject = x_mb[jnp.minimum(t, M - 1) % M]
        state = jnp.where(stage == 0, jnp.where(t < M, inject, state),
                          state)
        state = stage_fn(params, state)
        # The LAST stage has just finished microbatch t-(S-1).
        out_t = t - (S - 1)
        is_out = (stage == S - 1) & (out_t >= 0)
        outputs = jnp.where(
            is_out,
            jax.lax.dynamic_update_index_in_dim(
                outputs, state.astype(outputs.dtype),
                jnp.maximum(out_t, 0) % M, 0),
            outputs,
        )
        state = lax.ppermute(state, axis, perm)
        return (state, outputs), None

    (state, outputs), _ = lax.scan(
        tick, (state, outputs), jnp.arange(M + S - 1)
    )
    # Outputs live on the last stage only; broadcast around the ring so
    # every stage returns the same (replicated out_spec).
    outputs = lax.psum(
        jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)), axis
    )
    return outputs


def pipeline_apply(stage_fn, stage_params, x, mesh: Mesh,
                   n_microbatches: int, axis: str = "stage"):
    """Apply a stage-sharded layer stack to ``x`` through the pipeline.

    ``stage_fn(params_one_stage, x_mb) -> y_mb`` runs this stage's layer
    chunk on one microbatch. ``stage_params`` leaves carry a leading
    ``n_stages`` dim (from :func:`split_stages`), sharded over ``axis``.
    ``x``: (B, ...) with B divisible by ``n_microbatches``.
    """
    S = int(mesh.shape[axis])
    B = x.shape[0]
    if B % n_microbatches:
        raise ClusterError(
            f"pipeline: batch {B} not divisible into {n_microbatches} "
            "microbatches"
        )
    x_mb = x.reshape(n_microbatches, B // n_microbatches, *x.shape[1:])

    param_specs = jax.tree.map(
        lambda p: P(axis, *(None,) * (p.ndim - 1)), stage_params
    )
    fn = shard_map(
        partial(_spmd_pipeline, stage_fn, axis=axis, n_stages=S,
                n_microbatches=n_microbatches),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False,
    )
    y_mb = fn(stage_params, x_mb)
    return y_mb.reshape(B, *y_mb.shape[2:])


def _stage_attn(cfg):
    """Attention for blocks INSIDE the stage ring: the resolved impl
    (flash kernel on TPU under "auto" — a pipelined model shouldn't
    pay dense B·H·S² scores just because its layers are staged; the
    kernel's custom VJP differentiates under shard_map). Seq-parallel
    impls can't nest inside the stage ring — refuse rather than
    silently running dense."""
    from ptype_tpu.models import transformer as tfm

    if cfg.attn_impl in ("ring", "ulysses"):
        raise ClusterError(
            f"pipeline stages cannot nest seq-parallel attention "
            f"(attn_impl={cfg.attn_impl!r}); use auto/flash/xla")
    return tfm.resolve_attn_fn(cfg)


def schedule_info(n_stages: int, n_microbatches: int,
                  schedule: str = "gpipe") -> dict:
    """Tick/stash/bubble accounting for a schedule — the numbers the
    1F1B-vs-GPipe tradeoff is made of.

    One *tick* is one scan iteration of the compiled SPMD program.
    GPipe runs two uniform phases (a forward scan then, via autodiff,
    a reversed backward scan): every stage stashes ALL M microbatch
    activations for the backward. 1F1B runs ONE combined scan whose
    steady-state ticks each do one real forward AND one real backward
    microbatch — the live stash is bounded by the schedule depth
    (2S-1), NOT by M. That bound is the whole point: at a fixed
    activation budget, 1F1B can raise M until the bubble fraction
    (idle ticks / total ticks) is driven down, where GPipe's stash
    grows linearly with M and caps it first.
    """
    S, M = n_stages, n_microbatches
    if schedule == "gpipe":
        return {
            "ticks": 2 * (M + S - 1),
            "stash_microbatches": M,
            "bubble_fraction": (S - 1) / (M + S - 1),
        }
    if schedule == "1f1b":
        ticks = M + 2 * S - 1
        return {
            "ticks": ticks,
            "stash_microbatches": 2 * S - 1,
            "bubble_fraction": (2 * S - 2) / ticks,
        }
    raise ClusterError(f"unknown pipeline schedule {schedule!r}")


def _spmd_pipeline_1f1b(stage_fn, tail_fn, stage_params, wnorm, head,
                        x_mb, tgt_mb, mask_mb, *, axis: str,
                        n_stages: int, n_microbatches: int):
    """Hand-scheduled 1F1B inside shard_map: one scan, each tick runs
    one forward microbatch AND one (rematerialized-VJP) backward
    microbatch where the schedule has work for this stage.

    Schedule (0-based tick t, stage s):
    - forward of microbatch m at  t = m + s,
    - stage S-1 computes the tail (final-norm + LM head + loss) VJP in
      the same tick its forward finishes, carrying the cotangent one
      tick to its own backward,
    - backward of microbatch m at t = m + S + (S-1-s)  — so a stage's
      gap between fwd(m) and bwd(m) is 2S-1-2s ticks, which bounds the
      live input stash at 2S-1 (vs GPipe's M).

    Backward is recomputed from the stashed INPUT (``jax.vjp`` on the
    stage at backward time) — the per-stage rematerialization
    jax.checkpoint would do anyway, which is what keeps the stash to
    inputs instead of full VJP residuals.

    Returns per-stage block grads (leading singleton stage dim), the
    psum'd tail grads (norm/head), the input cotangents (stage 0), and
    unnormalized (nll_sum, denom) accumulators from stage S-1.
    """
    stage = lax.axis_index(axis)
    S, M = n_stages, n_microbatches
    params = jax.tree.map(lambda p: jnp.squeeze(p, axis=0), stage_params)
    mb_shape = x_mb.shape[1:]
    K = 2 * S  # stash ring slots (schedule bound is 2S-1)
    is_last = stage == S - 1
    is_first = stage == 0

    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]

    zeros_mb = jnp.zeros(mb_shape, x_mb.dtype)
    carry0 = {
        "fwd_in": zeros_mb,
        "bwd_ct": zeros_mb,
        "self_ct": zeros_mb,
        "stash": jnp.zeros((K, *mb_shape), x_mb.dtype),
        "gblocks": jax.tree.map(jnp.zeros_like, params),
        "gnorm": jnp.zeros_like(wnorm),
        "ghead": jnp.zeros_like(head),
        "xct": jnp.zeros_like(x_mb),
        "nll": jnp.float32(0.0),
        "den": jnp.float32(0.0),
    }

    def tick(c, t):
        # ---------------- forward op: microbatch m_f = t - stage
        m_f = t - stage
        fwd_valid = (m_f >= 0) & (m_f < M)
        m_f_c = jnp.clip(m_f, 0, M - 1)
        x_in = jnp.where(is_first, x_mb[m_f_c], c["fwd_in"])
        y = stage_fn(params, x_in)
        stash = jnp.where(
            fwd_valid,
            lax.dynamic_update_index_in_dim(c["stash"], x_in, t % K, 0),
            c["stash"])
        # Tail (norm+head+loss) VJP on the stage that just produced
        # final activations; its cotangent seeds this stage's OWN
        # backward next tick. Guarded by lax.cond — XLA's conditional
        # IS per-device control flow under manual shard_map, so only
        # stage S-1 pays the vocab matmul; a masked-but-computed tail
        # would burn (S-1)/S of the head FLOPs on results it discards
        # (advisor round-5 finding).
        tail_valid = is_last & fwd_valid

        def run_tail(y_in):
            (nll_m, den_m), tail_vjp = jax.vjp(
                lambda wn, hd, yy: tail_fn(wn, hd, yy, tgt_mb[m_f_c],
                                           mask_mb[m_f_c]),
                wnorm, head, y_in)
            dwn, dhd, dy = tail_vjp((jnp.float32(1.0), jnp.float32(0.0)))
            return (nll_m.astype(jnp.float32), den_m.astype(jnp.float32),
                    dwn, dhd, dy.astype(x_mb.dtype))

        def skip_tail(y_in):
            del y_in
            return (jnp.float32(0.0), jnp.float32(0.0),
                    jnp.zeros_like(wnorm), jnp.zeros_like(head),
                    zeros_mb)

        nll_m, den_m, dwn, dhd, self_ct = lax.cond(
            tail_valid, run_tail, skip_tail, y)
        nll = c["nll"] + nll_m
        den = c["den"] + den_m
        gnorm = c["gnorm"] + dwn
        ghead = c["ghead"] + dhd

        # --------------- backward op: microbatch m_b = t-(2S-1)+stage
        m_b = t - (2 * S - 1) + stage
        bwd_valid = (m_b >= 0) & (m_b < M)
        x_saved = c["stash"][(m_b + stage) % K]
        ct_in = jnp.where(is_last, c["self_ct"], c["bwd_ct"])
        _, stage_vjp = jax.vjp(stage_fn, params, x_saved)
        dparams, dx = stage_vjp(ct_in)
        gblocks = jax.tree.map(
            lambda acc, g: acc + jnp.where(bwd_valid, g, 0.0),
            c["gblocks"], dparams)
        m_b_c = jnp.clip(m_b, 0, M - 1)
        xct = jnp.where(
            is_first & bwd_valid,
            lax.dynamic_update_index_in_dim(c["xct"], dx, m_b_c, 0),
            c["xct"])

        # --------------- ring communication for the NEXT tick
        nxt = {
            "fwd_in": lax.ppermute(y, axis, fwd_perm),
            "bwd_ct": lax.ppermute(dx, axis, bwd_perm),
            "self_ct": self_ct,
            "stash": stash,
            "gblocks": gblocks,
            "gnorm": gnorm,
            "ghead": ghead,
            "xct": xct,
            "nll": nll,
            "den": den,
        }
        return nxt, None

    ticks = M + 2 * S - 1
    c, _ = lax.scan(tick, carry0, jnp.arange(ticks))

    # Stage-local accumulators → the global values each out_spec wants.
    last = is_last.astype(jnp.float32)
    first = is_first
    gblocks = jax.tree.map(lambda g: g[None], c["gblocks"])
    return (
        gblocks,
        lax.psum(c["gnorm"] * last, axis),
        lax.psum(c["ghead"] * last, axis),
        lax.psum(jnp.where(first, c["xct"],
                           jnp.zeros_like(c["xct"])), axis),
        lax.psum(c["nll"] * last, axis),
        lax.psum(c["den"] * last, axis),
    )


def pipeline_loss_and_grads_1f1b(params: dict, batch: dict, cfg,
                                 mesh: Mesh, n_microbatches: int,
                                 axis: str = "stage"):
    """(loss, grads) for the transformer with the block stack pipelined
    under the 1F1B schedule — the hand-written counterpart of
    ``jax.value_and_grad`` over :func:`transformer_pipeline_forward`
    (which autodiff turns into GPipe: full forward scan, then reversed
    backward scan, stashing all M microbatch activations per stage).
    Embedding lookup and its scatter-add gradient stay outside the
    ring, fed by the stage-0 input cotangents."""
    from ptype_tpu.models import transformer as tfm

    if cfg.n_experts:
        raise ClusterError(
            "pipeline parallelism does not support MoE configs yet "
            "(router aux loss would be dropped); use dp/fsdp/tp/ep")
    S = int(mesh.shape[axis])
    M = n_microbatches
    B, T = batch["tokens"].shape
    if B % M:
        raise ClusterError(
            f"pipeline: batch {B} not divisible into {M} microbatches")
    mb = B // M
    tokens_mb = batch["tokens"].reshape(M, mb, T)
    tgt_mb = batch["targets"].reshape(M, mb, T)
    # An all-ones mask is numerically identical to no mask (denom =
    # token count) and keeps the shard_map arg tree static.
    mask_mb = (jnp.ones((M, mb, T), jnp.float32)
               if batch.get("loss_mask") is None
               else batch["loss_mask"].reshape(M, mb, T))
    x_mb = params["embed"][tokens_mb].astype(cfg.dtype)
    sin, cos = tfm.rope_tables(cfg, T)
    stage_blocks = split_stages(params["blocks"], S)
    head = tfm._head_weight(params, cfg)
    wnorm = params["final_norm"]

    attn = _stage_attn(cfg)

    def stage_fn(blocks, x):
        def body(x, layer):
            x, _aux = tfm._block(x, layer, sin, cos, cfg, attn)
            return x, None

        if cfg.remat:
            body = jax.checkpoint(body)
        x, _ = lax.scan(body, x, blocks)
        return x

    def tail_fn(wn, hd, y, tgt, mask):
        x = tfm.rms_norm(y, wn)
        logits = tfm.head_logits(x, hd, cfg)
        return tfm.nll_terms_from_logits(
            logits, {"targets": tgt, "loss_mask": mask})

    param_specs = jax.tree.map(
        lambda p: P(axis, *(None,) * (p.ndim - 1)), stage_blocks)
    fn = shard_map(
        partial(_spmd_pipeline_1f1b, stage_fn, tail_fn, axis=axis,
                n_stages=S, n_microbatches=M),
        mesh=mesh,
        in_specs=(param_specs, P(), P(), P(), P(), P()),
        out_specs=(param_specs, P(), P(), P(), P(), P()),
        check_vma=False,
    )
    gblocks, gnorm, ghead, xct, nll, den = fn(
        stage_blocks, wnorm, head, x_mb, tgt_mb, mask_mb)

    # Unnormalized sums accumulate in-ring; normalize ONCE here so the
    # loss/grads are invariant to M (trainer.py's accumulation rule).
    loss = nll / den
    inv = (1.0 / den).astype(jnp.float32)

    def scale(g):
        return (g * inv).astype(g.dtype)

    # Embedding grad: scatter-add of the stage-0 input cotangents,
    # plus the tied head's transpose contribution.
    xct = xct.reshape(B, T, -1).astype(jnp.float32) * inv
    dembed = (jnp.zeros_like(params["embed"])
              .at[batch["tokens"]].add(xct))
    grads = {
        "blocks": jax.tree.map(scale, merge_stages(gblocks)),
        "final_norm": scale(gnorm),
        "embed": dembed,
    }
    if cfg.tie_embeddings:
        grads["embed"] = grads["embed"] + scale(ghead).T
    else:
        grads["lm_head"] = scale(ghead)
    return loss, grads


# ------------------------------------------------- transformer integration


def transformer_pipeline_forward(params: dict, tokens: jax.Array, cfg,
                                 mesh: Mesh, n_microbatches: int,
                                 axis: str = "stage") -> jax.Array:
    """models/transformer.forward with the block stack pipelined.

    Embedding and the LM head stay outside the pipeline (they are one
    matmul each); the L blocks split into ``stage``-many chunks. Same
    logits as the dense forward, modulo bf16 accumulation order.
    """
    from ptype_tpu.models import transformer as tfm

    if cfg.n_experts:
        # The stage ring carries activations only; threading the MoE
        # router aux loss through it is not implemented — refusing beats
        # silently optimizing a different objective than the dense path.
        raise ClusterError(
            "pipeline parallelism does not support MoE configs yet "
            "(router aux loss would be dropped); use dp/fsdp/tp/ep"
        )
    S = int(mesh.shape[axis])
    B, T = tokens.shape
    dt = cfg.dtype
    x = params["embed"][tokens].astype(dt)
    sin, cos = tfm.rope_tables(cfg, T)
    stage_blocks = split_stages(params["blocks"], S)
    attn = _stage_attn(cfg)

    def stage_fn(blocks, x_mb):
        def body(x, layer):
            x, _aux = tfm._block(x, layer, sin, cos, cfg, attn)
            return x, None

        if cfg.remat:
            body = jax.checkpoint(body)
        x_mb, _ = lax.scan(body, x_mb, blocks)
        return x_mb

    x = pipeline_apply(stage_fn, stage_blocks, x, mesh, n_microbatches,
                       axis)
    x = tfm.rms_norm(x, params["final_norm"])
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    return jnp.einsum("bsd,dv->bsv", x.astype(jnp.float32),
                      head.astype(jnp.float32))


def pipeline_state_shardings(params_like, mesh: Mesh, optimizer,
                             axis: str = "stage"):
    """NamedSharding pytree for a pipelined TrainState: block leaves
    shard their leading layer dim over ``axis`` (L = S·L/S, so the
    per-stage split is a local reshape), everything else replicated;
    optax moments mirror the params."""
    from ptype_tpu.train.trainer import TrainState

    def param_sh(path, leaf):
        top = getattr(path[0], "key", None) if path else None
        if top == "blocks":
            return NamedSharding(mesh, P(axis, *(None,) * (leaf.ndim - 1)))
        return NamedSharding(mesh, P())

    params_shape = jax.eval_shape(lambda: params_like) \
        if not hasattr(jax.tree.leaves(params_like)[0], "shape") \
        else params_like
    p_sh = jax.tree_util.tree_map_with_path(param_sh, params_shape)
    opt_shape = jax.eval_shape(optimizer.init, params_shape)

    from ptype_tpu.train.trainer import opt_state_shardings

    repl = NamedSharding(mesh, P())
    o_sh = opt_state_shardings(opt_shape, params_shape, p_sh, repl)
    return TrainState(p_sh, o_sh, repl)


def make_pipeline_train_step(cfg, mesh: Mesh, n_microbatches: int,
                             optimizer=None, axis: str = "stage",
                             state_shardings=None,
                             schedule: str = "gpipe"):
    """(state, batch) → (state, metrics) with the block stack pipelined.

    State layout matches train/trainer.py's TrainState, so checkpoints
    interchange between pipelined and dense training. Pass
    ``state_shardings`` (from :func:`pipeline_state_shardings`) to pin
    each stage's layers — and their Adam moments — to that stage's
    devices; without it the state is replicated (fine for tests, wrong
    for models sized to per-stage memory).

    ``schedule``: "gpipe" (autodiff: forward scan + reversed backward
    scan, stash = M microbatch activations/stage) or "1f1b"
    (hand-scheduled combined scan, stash bounded at 2S-1 — see
    :func:`schedule_info` for the accounting that makes 1F1B the
    memory-bound choice that lets M, and therefore the bubble, scale).
    """
    import optax

    from ptype_tpu.models import transformer as tfm
    from ptype_tpu.train.trainer import TrainState, default_optimizer

    optimizer = optimizer or default_optimizer()
    if schedule not in ("gpipe", "1f1b"):
        raise ClusterError(f"unknown pipeline schedule {schedule!r}")

    def loss_fn(p, batch):
        logits = transformer_pipeline_forward(
            p, batch["tokens"], cfg, mesh, n_microbatches, axis
        )
        return tfm.nll_from_logits(logits, batch)

    def step(state: TrainState, batch: dict):
        if schedule == "1f1b":
            loss, grads = pipeline_loss_and_grads_1f1b(
                state.params, batch, cfg, mesh, n_microbatches, axis)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(state.params,
                                                      batch)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new = TrainState(new_params, opt_state, state.step + 1)
        return new, {"loss": loss, "step": new.step}

    kw = {}
    if state_shardings is not None:
        kw = {"in_shardings": (state_shardings,
                               NamedSharding(mesh, P())),
              "out_shardings": (state_shardings,
                                {"loss": NamedSharding(mesh, P()),
                                 "step": NamedSharding(mesh, P())})}
    return jax.jit(step, donate_argnums=(0,), **kw)
