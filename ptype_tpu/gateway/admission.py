"""Admission control: the gateway's bounded waiting room.

Every request entering the gateway passes through one
:class:`AdmissionQueue` before it may touch a replica. The queue
enforces three invariants the raw RPC plane cannot:

- **bounded depth** — once ``max_depth`` requests are waiting, new
  arrivals are refused with a typed :class:`~ptype_tpu.errors.ShedError`
  carrying a retry-after hint, instead of piling onto socket buffers
  until everything times out (the overload mode the north star's
  "millions of users" traffic makes routine);
- **per-request deadlines** — a request that cannot be *started* before
  its deadline is shed at admit time (SLO-aware shedding: the estimated
  queue wait already exceeds the budget), and one whose deadline lapses
  *while queued* is shed the moment it would have been granted — a shed
  is a fast, typed, retryable answer; a timeout is a lost request;
- **concurrency capping** — at most ``capacity()`` requests are
  dispatched at once (the pool sizes this from live replicas), so a
  replica fleet is never concurrently oversubscribed past the point
  where every request's latency degrades together.

Chaos seam: ``gateway.admit`` (actions ``shed`` — force-refuse this
admission, ``delay`` — stall the admit path), wired exactly like the
PR-2 hooks; recoveries pair on the gateway class via the frontdoor's
success beacon.
"""

from __future__ import annotations

import threading
import time

from ptype_tpu import lockcheck

from ptype_tpu import chaos, logs
from ptype_tpu.errors import ShedError

log = logs.get_logger("gateway.admission")


class _Ticket:
    __slots__ = ("key", "deadline", "granted", "enq_t", "shed_reason")

    def __init__(self, key: str, deadline: float | None):
        self.key = key
        self.deadline = deadline
        self.granted = threading.Event()
        self.enq_t = time.monotonic()
        #: Set (with the event) when the queue refuses rather than
        #: grants — close() path; no dispatch slot was consumed.
        self.shed_reason: str | None = None


class AdmissionQueue:
    """FIFO waiting room with a dynamic concurrency cap.

    ``capacity`` is a callable (live replicas × per-replica in-flight
    limit — it changes as the pool evicts and revives replicas);
    ``est_service_s`` is a callable returning the current estimate of
    one request's service time (the SLO tracker's EWMA), used both for
    the admission-time deadline check and the shed retry-after hint.
    """

    def __init__(self, max_depth: int, capacity,
                 est_service_s=None):
        self.max_depth = int(max_depth)
        self._capacity = capacity
        self._est_service_s = est_service_s or (lambda: 0.1)
        self._lock = lockcheck.lock("gateway.admission")
        self._queue: list[_Ticket] = []
        self._inflight = 0
        self._closed = False
        # Shed accounting, by cause — the autoscale layer reads these.
        self.shed_full = 0
        self.shed_slo = 0
        self.shed_deadline = 0
        self.admitted = 0

    # -------------------------------------------------------------- admit

    def admit(self, key: str = "", deadline: float | None = None) -> None:
        """Block until this request may dispatch, or raise
        :class:`ShedError`. ``deadline`` is an absolute monotonic
        stamp. The caller MUST call :meth:`release` after its dispatch
        completes (success or failure)."""
        f = chaos.hit("gateway.admit", key)
        if f is not None:
            if f.action == "delay":
                f.sleep()
            elif f.action == "shed":
                with self._lock:
                    self.shed_slo += 1
                    ra = self._retry_after_locked()
                raise ShedError(
                    f"chaos: forced shed at admission ({key!r})",
                    retry_after_s=ra)
        with self._lock:
            if self._closed:
                raise ShedError("gateway is shutting down",
                                retry_after_s=1.0)
            if self._inflight < max(1, int(self._capacity())) \
                    and not self._queue:
                self._inflight += 1
                self.admitted += 1
                return
            if len(self._queue) >= self.max_depth:
                self.shed_full += 1
                raise ShedError(
                    f"admission queue full ({self.max_depth} waiting)",
                    retry_after_s=self._retry_after_locked())
            if deadline is not None:
                est_wait = ((len(self._queue) + 1)
                            * self._est_service_s()
                            / max(1, int(self._capacity())))
                if time.monotonic() + est_wait > deadline:
                    # SLO-aware shed: the queue alone already eats the
                    # budget — refuse NOW with a hint, don't make the
                    # caller discover it via a timeout.
                    self.shed_slo += 1
                    raise ShedError(
                        f"estimated queue wait {est_wait:.2f}s exceeds "
                        f"the request deadline",
                        retry_after_s=self._retry_after_locked())
            t = _Ticket(key, deadline)
            self._queue.append(t)
        timeout = (None if deadline is None
                   else max(0.0, deadline - time.monotonic()))
        if t.granted.wait(timeout=timeout):
            if t.shed_reason is not None:
                # Woken to be refused (close()): no slot was consumed.
                raise ShedError(t.shed_reason, retry_after_s=1.0)
            return
        # Deadline lapsed while queued. Two races to settle under the
        # lock: still queued (the common case — withdraw and shed), or
        # granted in the instant after wait() gave up (we own a slot:
        # return it before shedding).
        with self._lock:
            if t in self._queue:
                self._queue.remove(t)
            elif t.shed_reason is None:
                self._release_locked()
            self.shed_deadline += 1
            ra = self._retry_after_locked()
        raise ShedError("deadline lapsed in the admission queue",
                        retry_after_s=ra)

    def release(self) -> None:
        """One dispatched request finished; grant the next waiter."""
        with self._lock:
            self._release_locked()

    def poke(self) -> None:
        """Re-evaluate grants — call when capacity may have GROWN
        (replica revived/arrived); shrinkage self-corrects as in-flight
        requests drain."""
        with self._lock:
            self._pump_locked()

    # ------------------------------------------------------------ internal

    def _release_locked(self) -> None:
        self._inflight = max(0, self._inflight - 1)
        self._pump_locked()

    def _pump_locked(self) -> None:
        cap = max(1, int(self._capacity()))
        while self._queue and self._inflight < cap:
            t = self._queue.pop(0)
            self._inflight += 1
            self.admitted += 1
            t.granted.set()

    def _retry_after_locked(self) -> float:
        """Backlog-proportional hint: how long until the queue has
        plausibly drained one slot's worth of room for this caller;
        callers hold the lock (the queue length must be the one the
        shed decision was made against)."""
        est = ((len(self._queue) + 1) * self._est_service_s()
               / max(1, int(self._capacity())))
        return min(10.0, max(0.05, est))

    # ---------------------------------------------------------- inspection

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    @property
    def shed_total(self) -> int:
        with self._lock:
            return self.shed_full + self.shed_slo + self.shed_deadline

    def close(self) -> None:
        """Refuse new admissions and fail every waiter (typed)."""
        with self._lock:
            self._closed = True
            waiters, self._queue = self._queue, []
            self.shed_deadline += len(waiters)
            for t in waiters:
                t.shed_reason = "gateway is shutting down"
        for t in waiters:
            t.granted.set()
