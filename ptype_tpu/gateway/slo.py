"""SLO accounting and autoscale signals for the gateway.

Every request outcome lands here: answered (with latency and token
count), shed (by cause), expired, or errored. The tracker exports the
serving tail through the shared :class:`~ptype_tpu.metrics
.MetricsRegistry` (counters, gauges, and a latency histogram with
p50/p95/p99) and distills the state into a :class:`ScaleHint` — the
one-number signal the elastic replica reconciler
(:mod:`ptype_tpu.reconciler`, which polls ``gateway.scale_hint`` and
folds it through its hysteresis policy) or an external autoscaler
polling ``Gateway.Info`` consumes without understanding the
gateway's internals.

Metric names (under the process-global registry by default):

======================================  ================================
``gateway.<svc>.requests``              arrivals (counter)
``gateway.<svc>.answered``              successful responses (counter)
``gateway.<svc>.shed``                  typed sheds, all causes (counter)
``gateway.<svc>.errors``                non-shed failures (counter)
``gateway.<svc>.latency_ms``            answered-request latency (histogram)
``gateway.<svc>.ttft_ms``               replica-reported per-request TTFT
                                        (histogram; fed by the pool's
                                        probes from the serving ledger's
                                        ``ttft_recent`` samples)
``gateway.<svc>.queue_depth``           admission queue depth (gauge)
``gateway.<svc>.healthy_replicas``      routable fleet size (gauge)
``gateway.<svc>.scale_hint``            last computed hint delta (gauge)
``gateway.<svc>.slo_good_requests``     answered AND met the TTFT and
                                        TPOT SLOs (counter)
``gateway.<svc>.slo_violations``        everything else that arrived:
                                        sheds, errors, and answers
                                        over SLO (counter)
======================================  ================================

Goodput is first-class (ISSUE 19): the good/violation pair moves per
request, so the capacity frontier and the burn-rate math read a
*series*, never post-hoc percentile arithmetic. A request is good
only if TTFT **and** TPOT met their SLOs; when the dispatch path
cannot report a per-request TTFT (the interleaved path is not
streaming), the e2e latency stands in as a conservative upper bound —
TTFT ≤ e2e, so the fallback can only under-count goodput. The
disaggregated path reports its real TTFT (prefill completion is the
first token). With no SLOs configured every answer counts good, so
the counters stay meaningful as plain answered/failed accounting.
"""

from __future__ import annotations

import time

from ptype_tpu import lockcheck
from dataclasses import dataclass, field

from ptype_tpu import metrics as metrics_mod


@dataclass
class ScaleHint:
    """What the fleet should do: ``delta`` replicas (+N grow, -N
    shrink, 0 hold), with the deciding signal spelled out."""

    delta: int
    reason: str
    signals: dict = field(default_factory=dict)


class SLOTracker:
    """Windowed serving stats + the scale-hint policy.

    ``window_s`` bounds every rate (shed rate, tokens/sec) to recent
    traffic, so an hour-old burst cannot hold a scale-up hostage.
    """

    def __init__(self, service: str,
                 registry: metrics_mod.MetricsRegistry | None = None,
                 window_s: float = 30.0,
                 slo_p99_ms: float | None = None,
                 slo_ttft_p99_ms: float | None = None,
                 slo_tpot_p99_ms: float | None = None):
        self.service = service
        self.window_s = float(window_s)
        self.slo_p99_ms = slo_p99_ms
        self.slo_ttft_p99_ms = slo_ttft_p99_ms
        self.slo_tpot_p99_ms = slo_tpot_p99_ms
        reg = registry if registry is not None else metrics_mod.metrics
        self._reg = reg
        p = f"gateway.{service}"
        self.c_requests = reg.counter(f"{p}.requests")
        self.c_answered = reg.counter(f"{p}.answered")
        self.c_shed = reg.counter(f"{p}.shed")
        self.c_errors = reg.counter(f"{p}.errors")
        self.h_latency = reg.histogram(f"{p}.latency_ms")
        self.h_ttft = reg.histogram(f"{p}.ttft_ms")
        self.g_queue = reg.gauge(f"{p}.queue_depth")
        self.g_replicas = reg.gauge(f"{p}.healthy_replicas")
        self.g_hint = reg.gauge(f"{p}.scale_hint")
        self.c_good = reg.counter(f"{p}.slo_good_requests")
        self.c_violations = reg.counter(f"{p}.slo_violations")
        self._lock = lockcheck.lock("gateway.slo")
        #: (t, latency_ms, tokens) for answered requests in the window.
        self._ok: list[tuple[float, float, int]] = []
        #: (t,) stamps for sheds in the window.
        self._sheds: list[float] = []
        self._ewma_ms = 0.0

    # ------------------------------------------------------------ intake

    def arrived(self) -> None:
        self.c_requests.add(1)

    def answered(self, latency_ms: float, tokens: int = 0,
                 ttft_ms: float | None = None,
                 tpot_ms: float | None = None) -> None:
        self.c_answered.add(1)
        self.h_latency.observe(latency_ms)
        if self._good(latency_ms, ttft_ms, tpot_ms):
            self.c_good.add(1)
        else:
            self.c_violations.add(1)
        now = time.monotonic()
        with self._lock:
            self._ok.append((now, latency_ms, int(tokens)))
            self._trim(now)
            self._ewma_ms = (latency_ms if self._ewma_ms == 0.0
                             else 0.2 * latency_ms + 0.8 * self._ewma_ms)

    def _good(self, latency_ms: float, ttft_ms: float | None,
              tpot_ms: float | None) -> bool:
        """SLO attribution for ONE answered request (module docstring:
        missing TTFT falls back to e2e, the conservative bound; a
        TPOT SLO with no sample counts as met — a single-token answer
        has no inter-token gap to judge)."""
        if self.slo_ttft_p99_ms is not None:
            ttft = ttft_ms if ttft_ms is not None else latency_ms
            if ttft > self.slo_ttft_p99_ms:
                return False
        if (self.slo_tpot_p99_ms is not None and tpot_ms is not None
                and tpot_ms > self.slo_tpot_p99_ms):
            return False
        if (self.slo_ttft_p99_ms is None and self.slo_p99_ms is not None
                and latency_ms > self.slo_p99_ms):
            return False
        return True

    def shed(self) -> None:
        self.c_shed.add(1)
        self.c_violations.add(1)
        now = time.monotonic()
        with self._lock:
            self._sheds.append(now)
            self._trim(now)

    def record_ttft(self, ttft_ms: float) -> None:
        """Fold one replica-reported per-request TTFT sample. Fed by
        the replica pool's probe loop, which drains NEW
        (sequence-tagged) samples from each replica's serving-ledger
        ``ttft_recent`` — real per-request samples, never a
        percentile-of-percentile."""
        self.h_ttft.observe(float(ttft_ms))

    def errored(self) -> None:
        self.c_errors.add(1)
        self.c_violations.add(1)

    def _trim(self, now: float) -> None:
        cut = now - self.window_s
        while self._ok and self._ok[0][0] < cut:
            self._ok.pop(0)
        while self._sheds and self._sheds[0] < cut:
            self._sheds.pop(0)

    # ----------------------------------------------------------- readouts

    def est_service_s(self) -> float:
        """Current one-request service-time estimate (admission's
        SLO-aware shed math); a conservative floor before any data."""
        with self._lock:
            return (self._ewma_ms / 1000.0) if self._ewma_ms else 0.1

    def shed_rate(self) -> float:
        """Shed fraction of window traffic (sheds / (sheds + ok))."""
        with self._lock:
            self._trim(time.monotonic())
            total = len(self._sheds) + len(self._ok)
            return len(self._sheds) / total if total else 0.0

    def burn_rate(self, budget: float = 0.01) -> float:
        """Error-budget burn rate: the windowed shed fraction divided
        by the SLO's allowed bad fraction (default 1% — a 99%
        answered-SLO). 1.0 spends the budget exactly on schedule; the
        health plane's ``slo-burn-rate`` rule pages at the classic
        fast-burn multiple (14.4x) computed the same way from the
        sampled counter series, so the local and cluster views agree.
        """
        if budget <= 0:
            return 0.0
        return self.shed_rate() / budget

    def tokens_per_sec(self) -> float:
        with self._lock:
            self._trim(time.monotonic())
            if len(self._ok) < 2:
                return 0.0
            span = self._ok[-1][0] - self._ok[0][0]
            toks = sum(t for _, _, t in self._ok)
            return toks / span if span > 0 else 0.0

    def goodput(self) -> dict:
        """Lifetime SLO-attributed goodput: the good/violation split
        and the good fraction of everything that arrived and was
        resolved (answered + shed + errored)."""
        good = self.c_good.value
        bad = self.c_violations.value
        total = good + bad
        return {"slo_good_requests": int(good),
                "slo_violations": int(bad),
                "goodput_pct": (100.0 * good / total if total
                                else 100.0)}

    def percentiles(self) -> dict:
        return {"p50_ms": self.h_latency.percentile(50),
                "p95_ms": self.h_latency.percentile(95),
                "p99_ms": self.h_latency.percentile(99),
                "ttft_p50_ms": self.h_ttft.percentile(50),
                "ttft_p99_ms": self.h_ttft.percentile(99),
                **self.goodput()}

    # --------------------------------------------------------- scale hint

    def scale_hint(self, queue_depth: int, max_depth: int,
                   n_replicas: int, inflight: int,
                   capacity: int) -> ScaleHint:
        """Distill the window into one fleet-size delta.

        Priority order: shedding (capacity is actively short) beats a
        deep queue (capacity is about to be short) beats a TTFT SLO
        breach (prompt-heavy overload — queue + prefill wait blows the
        first token long before the e2e tail moves, which is exactly
        why a controller acting on e2e p99 alone scales too late)
        beats a p99 SLO breach (capacity is marginal) beats idle
        shrink. Hold otherwise. The hint is advisory — the elastic
        layer owns actuation and rate-limiting.
        """
        signals = {"queue_depth": queue_depth,
                   "shed_rate": round(self.shed_rate(), 4),
                   "n_replicas": n_replicas,
                   "inflight": inflight,
                   "capacity": capacity,
                   "tokens_per_sec": round(self.tokens_per_sec(), 1),
                   **{k: round(v, 2)
                      for k, v in self.percentiles().items()}}
        delta, reason = 0, "steady"
        per_replica = max(1, capacity // max(1, n_replicas))
        if signals["shed_rate"] > 0.0:
            # Backlog the queue could not absorb: size the step to the
            # standing queue, at least one replica.
            delta = max(1, queue_depth // per_replica)
            reason = "shedding load"
        elif max_depth and queue_depth >= max_depth // 2:
            delta = max(1, queue_depth // per_replica)
            reason = "admission queue above half depth"
        elif (self.slo_ttft_p99_ms is not None
              and self.h_ttft.count >= 20
              and signals["ttft_p99_ms"] > self.slo_ttft_p99_ms):
            delta = 1
            reason = (f"ttft p99 {signals['ttft_p99_ms']:.0f}ms over "
                      f"SLO {self.slo_ttft_p99_ms:.0f}ms")
        elif (self.slo_p99_ms is not None and self.h_latency.count >= 20
              and signals["p99_ms"] > self.slo_p99_ms):
            delta = 1
            reason = (f"p99 {signals['p99_ms']:.0f}ms over SLO "
                      f"{self.slo_p99_ms:.0f}ms")
        elif (n_replicas > 1 and queue_depth == 0
              and inflight * 3 < capacity):
            delta = -1
            reason = "fleet under a third utilized"
        self.g_hint.set(delta)
        return ScaleHint(delta=delta, reason=reason, signals=signals)
