"""SLO accounting and autoscale signals for the gateway.

Every request outcome lands here: answered (with latency and token
count), shed (by cause), expired, or errored. The tracker exports the
serving tail through the shared :class:`~ptype_tpu.metrics
.MetricsRegistry` (counters, gauges, and a latency histogram with
p50/p95/p99) and distills the state into a :class:`ScaleHint` — the
one-number signal the elastic replica reconciler
(:mod:`ptype_tpu.reconciler`, which polls ``gateway.scale_hint`` and
folds it through its hysteresis policy) or an external autoscaler
polling ``Gateway.Info`` consumes without understanding the
gateway's internals.

Metric names (under the process-global registry by default):

======================================  ================================
``gateway.<svc>.requests``              arrivals (counter)
``gateway.<svc>.answered``              successful responses (counter)
``gateway.<svc>.shed``                  typed sheds, all causes (counter)
``gateway.<svc>.errors``                non-shed failures (counter)
``gateway.<svc>.latency_ms``            answered-request latency (histogram)
``gateway.<svc>.ttft_ms``               replica-reported per-request TTFT
                                        (histogram; fed by the pool's
                                        probes from the serving ledger's
                                        ``ttft_recent`` samples)
``gateway.<svc>.queue_depth``           admission queue depth (gauge)
``gateway.<svc>.healthy_replicas``      routable fleet size (gauge)
``gateway.<svc>.scale_hint``            last computed hint delta (gauge)
``gateway.<svc>.slo_good_requests``     answered AND met the TTFT and
                                        TPOT SLOs (counter)
``gateway.<svc>.slo_violations``        everything else that arrived:
                                        sheds, errors, and answers
                                        over SLO (counter)
``gateway.<svc>.stage_ms.<stage>``      per-request time in one named
                                        pipeline stage (histogram; the
                                        ``slo-stage-breach`` rule reads
                                        the sampled ``.p99`` series)
``gateway.<svc>.exemplar_dumps``        SLO-violating requests that
                                        landed a full flight-recorder
                                        dump (counter; rate-limited by
                                        ``trace.maybe_dump``)
======================================  ================================

Goodput is first-class (ISSUE 19): the good/violation pair moves per
request, so the capacity frontier and the burn-rate math read a
*series*, never post-hoc percentile arithmetic. A request is good
only if TTFT **and** TPOT met their SLOs; when the dispatch path
cannot report a per-request TTFT (the interleaved path is not
streaming), the e2e latency stands in as a conservative upper bound —
TTFT ≤ e2e, so the fallback can only under-count goodput. The
disaggregated path reports its real TTFT (prefill completion is the
first token). With no SLOs configured every answer counts good, so
the counters stay meaningful as plain answered/failed accounting.
"""

from __future__ import annotations

import threading
import time

from ptype_tpu import lockcheck
from dataclasses import dataclass, field

from ptype_tpu import metrics as metrics_mod
from ptype_tpu import trace as trace_mod

#: Worst-value slots kept per reservoir metric (TTFT / TPOT): the
#: bounded tail-exemplar memory ``obs tail`` and ``Gateway.Info``
#: surface. Small on purpose — links to replayable traces, not a
#: second histogram.
WORST_SLOTS = 8


@dataclass
class ScaleHint:
    """What the fleet should do: ``delta`` replicas (+N grow, -N
    shrink, 0 hold), with the deciding signal spelled out."""

    delta: int
    reason: str
    signals: dict = field(default_factory=dict)


class Stopwatch:
    """The gateway's ONE latency clock. Raw ``time.perf_counter()``
    pairs beside the dispatch code rotted into three slightly
    different stamps before ISSUE 20; PT025 now forbids ad-hoc
    perf_counter latency measurement in ``gateway/`` and
    ``serve_engine/`` — attribution has one home (this class and the
    serving ledger), so every latency the SLO tracker, the stage
    histograms, and the traffic ledger see is the same clock."""

    __slots__ = ("_t0",)

    def __init__(self):
        self._t0 = time.perf_counter()

    def ms(self) -> float:
        """Elapsed wall milliseconds since construction."""
        return (time.perf_counter() - self._t0) * 1000.0

    def s(self) -> float:
        """Elapsed wall seconds since construction."""
        return time.perf_counter() - self._t0


class SLOTracker:
    """Windowed serving stats + the scale-hint policy.

    ``window_s`` bounds every rate (shed rate, tokens/sec) to recent
    traffic, so an hour-old burst cannot hold a scale-up hostage.
    """

    def __init__(self, service: str,
                 registry: metrics_mod.MetricsRegistry | None = None,
                 window_s: float = 30.0,
                 slo_p99_ms: float | None = None,
                 slo_ttft_p99_ms: float | None = None,
                 slo_tpot_p99_ms: float | None = None):
        self.service = service
        self.window_s = float(window_s)
        self.slo_p99_ms = slo_p99_ms
        self.slo_ttft_p99_ms = slo_ttft_p99_ms
        self.slo_tpot_p99_ms = slo_tpot_p99_ms
        reg = registry if registry is not None else metrics_mod.metrics
        self._reg = reg
        p = f"gateway.{service}"
        self.c_requests = reg.counter(f"{p}.requests")
        self.c_answered = reg.counter(f"{p}.answered")
        self.c_shed = reg.counter(f"{p}.shed")
        self.c_errors = reg.counter(f"{p}.errors")
        self.h_latency = reg.histogram(f"{p}.latency_ms")
        self.h_ttft = reg.histogram(f"{p}.ttft_ms")
        self.g_queue = reg.gauge(f"{p}.queue_depth")
        self.g_replicas = reg.gauge(f"{p}.healthy_replicas")
        self.g_hint = reg.gauge(f"{p}.scale_hint")
        self.c_good = reg.counter(f"{p}.slo_good_requests")
        self.c_violations = reg.counter(f"{p}.slo_violations")
        self.c_exemplar_dumps = reg.counter(f"{p}.exemplar_dumps")
        self._lock = lockcheck.lock("gateway.slo")
        #: (t, latency_ms, tokens) for answered requests in the window.
        self._ok: list[tuple[float, float, int]] = []
        #: (t,) stamps for sheds in the window.
        self._sheds: list[float] = []
        self._ewma_ms = 0.0
        #: Per-stage latency histograms, lazily created on first
        #: :meth:`stage` call (``gateway.<svc>.stage_ms.<stage>``).
        self._h_stage: dict[str, metrics_mod.Histogram] = {}
        #: Bounded worst-TTFT / worst-TPOT exemplar reservoirs:
        #: worst-first dicts with trace ids and stage splits attached.
        self._worst_ttft: list[dict] = []
        self._worst_tpot: list[dict] = []
        #: The calling thread's last answered request (trace id +
        #: stage split) — the loadgen driver's attribution seam.
        self._tls = threading.local()

    # ------------------------------------------------------------ intake

    def arrived(self) -> None:
        self.c_requests.add(1)

    def answered(self, latency_ms: float, tokens: int = 0,
                 ttft_ms: float | None = None,
                 tpot_ms: float | None = None,
                 stages: dict | None = None,
                 trace_id: str | None = None) -> None:
        if trace_id is None:
            trace_id = trace_mod.current_trace_id()
        self.c_answered.add(1)
        self.h_latency.observe(latency_ms, trace_id)
        if stages:
            for name, ms in stages.items():
                self.stage(name, ms, trace_id)
        ok = self._good(latency_ms, ttft_ms, tpot_ms)
        if ok:
            self.c_good.add(1)
        else:
            self.c_violations.add(1)
            # The tail-exemplar lifecycle (ISSUE 20): an SLO-violating
            # request dumps the whole flight ring (rate-limited inside
            # maybe_dump) so the p99 links to a replayable trace.
            if trace_mod.maybe_dump(
                    f"slo-violation:{self.service}") is not None:
                self.c_exemplar_dumps.add(1)
        self._note_worst(latency_ms, ttft_ms, tpot_ms, stages,
                         trace_id, ok)
        now = time.monotonic()
        with self._lock:
            self._ok.append((now, latency_ms, int(tokens)))
            self._trim(now)
            self._ewma_ms = (latency_ms if self._ewma_ms == 0.0
                             else 0.2 * latency_ms + 0.8 * self._ewma_ms)

    def stage(self, name: str, ms: float,
              trace_id: str | None = None) -> None:
        """Record one request's time in one named pipeline stage into
        ``gateway.<svc>.stage_ms.<name>`` — the histograms the health
        sampler stamps into ``...stage_ms.<name>.p99`` series and the
        ``slo-stage-breach`` rule prices against its budget table."""
        h = self._h_stage.get(name)
        if h is None:
            h = self._h_stage[name] = self._reg.histogram(
                f"gateway.{self.service}.stage_ms.{name}")
        h.observe(float(ms), trace_id)

    def _note_worst(self, latency_ms: float, ttft_ms: float | None,
                    tpot_ms: float | None, stages: dict | None,
                    trace_id: str | None, ok: bool) -> None:
        """Fold one answered request into the worst-TTFT/TPOT
        reservoirs and the thread-local last-request slot."""
        entry = {"latency_ms": round(latency_ms, 3),
                 "ttft_ms": (round(ttft_ms, 3)
                             if ttft_ms is not None else None),
                 "tpot_ms": (round(tpot_ms, 3)
                             if tpot_ms is not None else None),
                 "trace_id": trace_id,
                 "stages": dict(stages) if stages else {},
                 "slo_ok": ok, "ts": round(time.time(), 3)}
        self._tls.last = entry
        # TTFT falls back to e2e — same conservative bound _good uses.
        ttft = ttft_ms if ttft_ms is not None else latency_ms
        with self._lock:
            self._fold_worst(self._worst_ttft, ttft, entry)
            if tpot_ms is not None:
                self._fold_worst(self._worst_tpot, tpot_ms, entry)

    @staticmethod
    def _fold_worst(res: list[dict], value: float, entry: dict) -> None:
        item = {"value_ms": round(float(value), 3), **entry}
        if len(res) < WORST_SLOTS:
            res.append(item)
        else:
            i = min(range(len(res)), key=lambda j: res[j]["value_ms"])
            if value > res[i]["value_ms"]:
                res[i] = item

    def worst(self, limit: int = WORST_SLOTS) -> dict:
        """Worst-first TTFT/TPOT exemplar reservoirs — each entry
        carries the trace id and the per-stage split, so a tail
        number is one ``obs request <trace_id>`` from its waterfall."""
        with self._lock:
            ttft = sorted(self._worst_ttft,
                          key=lambda e: -e["value_ms"])[:limit]
            tpot = sorted(self._worst_tpot,
                          key=lambda e: -e["value_ms"])[:limit]
        return {"ttft": ttft, "tpot": tpot}

    def last_request(self) -> dict | None:
        """The calling thread's most recent answered request (trace
        id, stage split, SLO verdict) — how an in-process driver
        (loadgen's ``gateway_target``) attributes each outcome to its
        culprit stage without a second measurement path."""
        return getattr(self._tls, "last", None)

    def _good(self, latency_ms: float, ttft_ms: float | None,
              tpot_ms: float | None) -> bool:
        """SLO attribution for ONE answered request (module docstring:
        missing TTFT falls back to e2e, the conservative bound; a
        TPOT SLO with no sample counts as met — a single-token answer
        has no inter-token gap to judge)."""
        if self.slo_ttft_p99_ms is not None:
            ttft = ttft_ms if ttft_ms is not None else latency_ms
            if ttft > self.slo_ttft_p99_ms:
                return False
        if (self.slo_tpot_p99_ms is not None and tpot_ms is not None
                and tpot_ms > self.slo_tpot_p99_ms):
            return False
        if (self.slo_ttft_p99_ms is None and self.slo_p99_ms is not None
                and latency_ms > self.slo_p99_ms):
            return False
        return True

    def shed(self) -> None:
        self.c_shed.add(1)
        self.c_violations.add(1)
        now = time.monotonic()
        with self._lock:
            self._sheds.append(now)
            self._trim(now)

    def record_ttft(self, ttft_ms: float) -> None:
        """Fold one replica-reported per-request TTFT sample. Fed by
        the replica pool's probe loop, which drains NEW
        (sequence-tagged) samples from each replica's serving-ledger
        ``ttft_recent`` — real per-request samples, never a
        percentile-of-percentile."""
        self.h_ttft.observe(float(ttft_ms))

    def errored(self) -> None:
        self.c_errors.add(1)
        self.c_violations.add(1)

    def _trim(self, now: float) -> None:
        cut = now - self.window_s
        while self._ok and self._ok[0][0] < cut:
            self._ok.pop(0)
        while self._sheds and self._sheds[0] < cut:
            self._sheds.pop(0)

    # ----------------------------------------------------------- readouts

    def est_service_s(self) -> float:
        """Current one-request service-time estimate (admission's
        SLO-aware shed math); a conservative floor before any data."""
        with self._lock:
            return (self._ewma_ms / 1000.0) if self._ewma_ms else 0.1

    def shed_rate(self) -> float:
        """Shed fraction of window traffic (sheds / (sheds + ok))."""
        with self._lock:
            self._trim(time.monotonic())
            total = len(self._sheds) + len(self._ok)
            return len(self._sheds) / total if total else 0.0

    def burn_rate(self, budget: float = 0.01) -> float:
        """Error-budget burn rate: the windowed shed fraction divided
        by the SLO's allowed bad fraction (default 1% — a 99%
        answered-SLO). 1.0 spends the budget exactly on schedule; the
        health plane's ``slo-burn-rate`` rule pages at the classic
        fast-burn multiple (14.4x) computed the same way from the
        sampled counter series, so the local and cluster views agree.
        """
        if budget <= 0:
            return 0.0
        return self.shed_rate() / budget

    def tokens_per_sec(self) -> float:
        with self._lock:
            self._trim(time.monotonic())
            if len(self._ok) < 2:
                return 0.0
            span = self._ok[-1][0] - self._ok[0][0]
            toks = sum(t for _, _, t in self._ok)
            return toks / span if span > 0 else 0.0

    def goodput(self) -> dict:
        """Lifetime SLO-attributed goodput: the good/violation split
        and the good fraction of everything that arrived and was
        resolved (answered + shed + errored)."""
        good = self.c_good.value
        bad = self.c_violations.value
        total = good + bad
        return {"slo_good_requests": int(good),
                "slo_violations": int(bad),
                "goodput_pct": (100.0 * good / total if total
                                else 100.0)}

    def percentiles(self) -> dict:
        return {"p50_ms": self.h_latency.percentile(50),
                "p95_ms": self.h_latency.percentile(95),
                "p99_ms": self.h_latency.percentile(99),
                "ttft_p50_ms": self.h_ttft.percentile(50),
                "ttft_p99_ms": self.h_ttft.percentile(99),
                **self.goodput()}

    # --------------------------------------------------------- scale hint

    def scale_hint(self, queue_depth: int, max_depth: int,
                   n_replicas: int, inflight: int,
                   capacity: int) -> ScaleHint:
        """Distill the window into one fleet-size delta.

        Priority order: shedding (capacity is actively short) beats a
        deep queue (capacity is about to be short) beats a TTFT SLO
        breach (prompt-heavy overload — queue + prefill wait blows the
        first token long before the e2e tail moves, which is exactly
        why a controller acting on e2e p99 alone scales too late)
        beats a p99 SLO breach (capacity is marginal) beats idle
        shrink. Hold otherwise. The hint is advisory — the elastic
        layer owns actuation and rate-limiting.
        """
        signals = {"queue_depth": queue_depth,
                   "shed_rate": round(self.shed_rate(), 4),
                   "n_replicas": n_replicas,
                   "inflight": inflight,
                   "capacity": capacity,
                   "tokens_per_sec": round(self.tokens_per_sec(), 1),
                   **{k: round(v, 2)
                      for k, v in self.percentiles().items()}}
        delta, reason = 0, "steady"
        per_replica = max(1, capacity // max(1, n_replicas))
        if signals["shed_rate"] > 0.0:
            # Backlog the queue could not absorb: size the step to the
            # standing queue, at least one replica.
            delta = max(1, queue_depth // per_replica)
            reason = "shedding load"
        elif max_depth and queue_depth >= max_depth // 2:
            delta = max(1, queue_depth // per_replica)
            reason = "admission queue above half depth"
        elif (self.slo_ttft_p99_ms is not None
              and self.h_ttft.count >= 20
              and signals["ttft_p99_ms"] > self.slo_ttft_p99_ms):
            delta = 1
            reason = (f"ttft p99 {signals['ttft_p99_ms']:.0f}ms over "
                      f"SLO {self.slo_ttft_p99_ms:.0f}ms")
        elif (self.slo_p99_ms is not None and self.h_latency.count >= 20
              and signals["p99_ms"] > self.slo_p99_ms):
            delta = 1
            reason = (f"p99 {signals['p99_ms']:.0f}ms over SLO "
                      f"{self.slo_p99_ms:.0f}ms")
        elif (n_replicas > 1 and queue_depth == 0
              and inflight * 3 < capacity):
            delta = -1
            reason = "fleet under a third utilized"
        self.g_hint.set(delta)
        return ScaleHint(delta=delta, reason=reason, signals=signals)
