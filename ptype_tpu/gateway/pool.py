"""Replica pool: the gateway's live map of the serving fleet.

One :class:`Replica` per registry node of the fronted service, each
owning its own multiplexed connection (dialed through the same
``rpc._dial`` seam the balancer uses, so the in-process zero-copy fast
path and the ``rpc.dial`` chaos site both apply). Two background
threads keep the map honest:

- the **watch thread** consumes the registry's snapshot stream
  (``watch_service`` → :meth:`NodeWatch.latest`, so churn bursts
  collapse to the final membership) and adds/removes replicas;
- the **probe thread** runs active health checks: an ``Info()``
  round-trip per replica per interval, feeding a per-replica EWMA
  latency and the replica-reported ``in_flight``/``queue_depth``
  (serve.py exports them). ``eviction_threshold`` consecutive probe
  failures evict the replica (connection closed, no traffic routed);
  every later round re-dials, so a recovered replica is revived
  without operator action. Probes also drain the paged engine's
  recent per-request TTFT samples (``ttft_recent`` in ``Info()``,
  sequence-tagged) into the ``on_ttft`` callback — the gateway's SLO
  tracker folds them, so its fleet-level TTFT percentiles are fed
  from real per-request samples rather than a replica percentile of
  percentiles; the high-water mark per replica keeps overlapping
  probe windows from double-counting.

Routing (:meth:`pick`) replaces the RPC plane's blind round-robin:

- **least-loaded** — lowest estimated completion time: (locally
  tracked in-flight + replica-reported backlog + 1) × EWMA service
  latency, so a slow OR backed-up replica sheds traffic to its healthy
  siblings instead of serializing callers behind it;
- **prefix-affinity** (optional) — requests carrying an affinity key
  hash (FNV-1a, the balancer's own function) to a stable replica so
  its KV/prefix caches stay warm, UNLESS that replica's load exceeds
  the least-loaded choice by more than ``affinity_slack`` — affinity
  must never pin traffic to a wedged node.

Chaos seams: ``gateway.probe`` (``drop``/``timeout`` — fail this probe,
``delay`` — slow it) and ``gateway.route`` (``drop`` — veto the picked
replica, forcing the route elsewhere; ``delay``).
"""

from __future__ import annotations

import threading
import time

from ptype_tpu import lockcheck

from ptype_tpu import chaos, logs, retry, rpc as rpc_mod
from ptype_tpu.gateway.slo import Stopwatch
from ptype_tpu.registry import Node, Registry

log = logs.get_logger("gateway.pool")


class Replica:
    """One fleet member: connection, load estimate, health state."""

    def __init__(self, node: Node):
        self.node = node
        self.key = f"{node.address}:{node.port}"
        self.conn = None
        self.inflight = 0          # locally dispatched, not yet done
        #: EWMA of CALL latencies only. 0.0 = never called.
        self.ewma_ms = 0.0
        #: EWMA of probe (Info) round-trips, kept SEPARATE: probes are
        #: cheap control-plane calls, and folding their ~1 ms RTTs into
        #: the call EWMA would decay a degraded replica's slow-call
        #: signal back to "fast" between requests.
        self.probe_ms = 0.0
        self.reported: dict = {}   # last Info() payload
        #: High-water ``ttft_recent`` sequence already drained — the
        #: replica's ledger tags samples so probes never double-count.
        self.ttft_seen = 0
        self.fails = 0             # consecutive probe failures
        self.up = False
        self.dialing = False       # one (re)dial in flight at a time
        self.calls = 0
        self.lock = lockcheck.lock("gateway.pool.replica")

    def score(self) -> float:
        """Estimated ms until this replica would finish MY request:
        (backlog ahead of me + me) × EWMA service time. Lower =
        preferred. A scalar, not (backlog, latency) lexicographic — a
        tuple would route to an idle-but-slow replica over a
        busy-but-fast one, which is exactly the slow-replica trap
        least-loaded routing exists to avoid. The latency estimate is
        the WORSE of the call and probe EWMAs: calls catch a replica
        whose compute degraded but whose Info stays fast; probes catch
        one that is slow before it has served any call."""
        with self.lock:
            backlog = self.inflight + int(
                self.reported.get("queue_depth", 0) or 0)
            return (backlog + 1) * max(self.ewma_ms, self.probe_ms, 1.0)

    def observe_ms(self, ms: float, alpha: float) -> None:
        with self.lock:
            self.ewma_ms = (ms if self.ewma_ms == 0.0
                            else alpha * ms + (1 - alpha) * self.ewma_ms)

    def observe_probe_ms(self, ms: float, alpha: float) -> None:
        with self.lock:
            self.probe_ms = (ms if self.probe_ms == 0.0
                             else alpha * ms + (1 - alpha) * self.probe_ms)

    def kv_free_blocks(self) -> int | None:
        """Replica-reported paged-KV admission headroom (None: the
        replica doesn't run the paged engine / hasn't been probed).
        The affinity router yields past an exhausted pool — an
        affinity hit that sheds is worse than a cold miss elsewhere."""
        with self.lock:
            v = self.reported.get("kv_free_blocks")
            return None if v is None else int(v)

    def lifecycle(self) -> str | None:
        """Replica-reported lifecycle (ISSUE 13: spawning/warm/active/
        draining — the reconciler's state machine, surfaced through
        ``Info()``). None until a probe observes it. A draining
        replica sheds every new request typed, so routing sorts it
        last and affinity yields past it — the same treatment as an
        exhausted KV pool."""
        with self.lock:
            v = self.reported.get("lifecycle")
            return None if v is None else str(v)

    def serve_class(self) -> str | None:
        """Replica-reported serving class (ISSUE 16: ``prefill`` /
        ``decode`` / ``unified``). None until a probe observes it —
        the two-stage router treats an unclassed replica as unified
        (it serves every endpoint, classes are advisory)."""
        with self.lock:
            v = self.reported.get("serve_class")
            return None if v is None else str(v)

    def domain(self) -> int | None:
        """Topology domain this replica lives in (the fast-ICI island,
        parallel/topology.py). The registry advertisement
        (``node.metadata["domain"]``, stamped by the launcher) wins;
        else the probe-reported value; None when neither side is
        topology-aware — the whole fleet then shares one implicit
        domain and every locality preference is a no-op."""
        meta = getattr(self.node, "metadata", None) or {}
        v = meta.get("domain")
        if v is None:
            with self.lock:
                v = self.reported.get("domain")
        try:
            return None if v is None else int(v)
        except (TypeError, ValueError):
            return None

    def kv_evictions(self) -> int | None:
        """Replica-reported cumulative LRU eviction count
        (``kv_evictions`` in ``BlockPool.stats``): the prefix
        directory's coherence signal — any movement drops the
        replica's directory entries before the router trusts them."""
        with self.lock:
            v = self.reported.get("kv_evictions")
            return None if v is None else int(v)

    def reported_float(self, key: str) -> float | None:
        """One probe-reported numeric field, or None (absent replica
        surface / malformed value) — the per-class scale hints read
        ledger tails (``tpot_p99_ms``) through this."""
        with self.lock:
            v = self.reported.get(key)
        try:
            return None if v is None else float(v)
        except (TypeError, ValueError):
            return None

    def snapshot(self) -> dict:
        dom = self.domain()  # resolved before taking the lock
        with self.lock:
            snap = {"key": self.key, "up": self.up,
                    "inflight": self.inflight, "calls": self.calls,
                    "ewma_ms": round(max(self.ewma_ms, self.probe_ms),
                                     3),
                    "call_ewma_ms": round(self.ewma_ms, 3),
                    "probe_ewma_ms": round(self.probe_ms, 3),
                    "fails": self.fails,
                    "reported_queue_depth":
                        int(self.reported.get("queue_depth", 0) or 0),
                    "reported_in_flight":
                        int(self.reported.get("in_flight", 0) or 0)}
            # Lifecycle column (ISSUE 13): the fleet view matches the
            # reconciler's state machine — only when reported, so a
            # bare actor with no lifecycle story stays distinguishable.
            if "lifecycle" in self.reported:
                snap["lifecycle"] = str(self.reported["lifecycle"])
            # Serving class + migration counters (ISSUE 16): the
            # disaggregated-fleet view — only when reported, same as
            # lifecycle, so pre-disagg replicas render "-".
            if "serve_class" in self.reported:
                snap["serve_class"] = str(self.reported["serve_class"])
            for k in ("migrations", "migrate_bytes",
                      "migrate_dedup_hits", "migrate_inflight"):
                if k in self.reported:
                    snap[k] = int(self.reported[k] or 0)
            # Paged-engine load signal (ISSUE 9): pool headroom and
            # prefix-cache effectiveness, when the replica reports it.
            if "kv_free_blocks" in self.reported:
                snap["kv_free_blocks"] = int(
                    self.reported["kv_free_blocks"] or 0)
            if "prefix_hit_rate" in self.reported:
                snap["prefix_hit_rate"] = float(
                    self.reported["prefix_hit_rate"] or 0.0)
            # Speculative-decoding accept rate (ISSUE 12): reported
            # only by spec-armed replicas, so a collapsed rate is
            # visible fleet-wide without faking 0.0 on the rest.
            if "spec_accept_rate" in self.reported:
                snap["spec_accept_rate"] = float(
                    self.reported["spec_accept_rate"] or 0.0)
            # Topology domain (ISSUE 18): the ``obs topo`` view's
            # per-domain replica counts — only when advertised.
            if dom is not None:
                snap["domain"] = dom
            return snap


class ReplicaPool:
    """Watch + probe + route over every replica of one service."""

    def __init__(self, registry: Registry, service: str,
                 info_method: str = "Generator.Info",
                 probe_interval: float = 1.0,
                 probe_timeout: float = 2.0,
                 eviction_threshold: int = 3,
                 ewma_alpha: float = 0.3,
                 dial_timeout: float = 2.0,
                 affinity_slack: float = 3.0,
                 on_change=None, on_ttft=None):
        self.service = service
        self.info_method = info_method
        self.probe_interval = probe_interval
        self.probe_timeout = probe_timeout
        self.eviction_threshold = int(eviction_threshold)
        self.ewma_alpha = ewma_alpha
        self.dial_timeout = dial_timeout
        self.affinity_slack = float(affinity_slack)
        self._on_change = on_change or (lambda: None)
        #: ``on_ttft(ttft_ms)`` per NEW replica-reported per-request
        #: TTFT sample (the gateway wires SLOTracker.record_ttft).
        self._on_ttft = on_ttft
        self._lock = lockcheck.lock("gateway.pool.fleet")
        self._replicas: dict[str, Replica] = {}
        self._closed = threading.Event()
        self._watch = registry.watch_service(service)
        # First snapshot synchronously (the registry pushes one
        # immediately): the gateway is routable the moment it
        # constructs, instead of racing its first request against the
        # watch thread.
        initial = self._watch.latest(timeout=2.0)
        if initial:
            self._sync(initial)
            self.probe_now()
        self._watch_thread = threading.Thread(
            target=self._watch_loop, name=f"gw-watch-{service}",
            daemon=True)
        self._watch_thread.start()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name=f"gw-probe-{service}",
            daemon=True)
        self._probe_thread.start()

    # --------------------------------------------------------- membership

    def _watch_loop(self) -> None:
        while not self._closed.is_set():
            snap = self._watch.latest(timeout=0.5)
            if snap is None:
                if self._watch.closed:
                    return
                continue
            self._sync(snap)
            self.probe_now()

    def _sync(self, nodes: list[Node]) -> None:
        wanted = {f"{n.address}:{n.port}": n for n in nodes}
        dropped: list[Replica] = []
        with self._lock:
            for key in list(self._replicas):
                if key not in wanted:
                    dropped.append(self._replicas.pop(key))
            for key, node in wanted.items():
                if key not in self._replicas:
                    self._replicas[key] = Replica(node)
        for r in dropped:
            self._close_conn(r)
            log.info("replica left the fleet", kv={"replica": r.key})
        self._on_change()

    # ------------------------------------------------------------- probes

    def _probe_loop(self) -> None:
        bo = retry.Backoff(base=self.probe_interval,
                           cap=self.probe_interval, jitter=0.25)
        while not self._closed.is_set():
            bo.wait(self._closed)
            if self._closed.is_set():
                return
            self.probe_now()

    def probe_now(self) -> None:
        """One probe round over the whole fleet (also the re-dial
        path: an evicted replica that answers again is revived).
        Probes run CONCURRENTLY — one blackholed node must not stretch
        the whole fleet's round by its dial timeout, staling the load
        data routing depends on. The bounded join keeps rounds from
        stacking; a straggler past it finishes in the background
        (per-replica ``dialing`` serializes re-dials, and a probe that
        loses the race with close() discards its connection)."""
        reps = [r for r in self._snapshot_replicas()]
        if not reps or self._closed.is_set():
            return
        if len(reps) == 1:
            self._probe_one(reps[0])
            return
        threads = [threading.Thread(target=self._probe_one, args=(r,),
                                    name=f"gw-probe-{r.key}",
                                    daemon=True)
                   for r in reps]
        for t in threads:
            t.start()
        deadline = (time.monotonic() + self.dial_timeout
                    + self.probe_timeout + 1.0)
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    def _probe_one(self, r: Replica) -> None:
        f = chaos.hit("gateway.probe", r.key)
        if f is not None:
            if f.action == "delay":
                f.sleep()
            elif f.action in ("drop", "timeout"):
                self._probe_failed(r, f"chaos: probe {f.action}")
                return
        conn = self._ensure_conn(r)
        if conn is None:
            self._probe_failed(r, "dial failed")
            return
        sw = Stopwatch()
        fut = None
        try:
            fut = conn.call_async(self.info_method, ())
            info = fut.result(timeout=self.probe_timeout)
        except Exception as e:  # noqa: BLE001 — any failure = unhealthy
            if fut is not None:
                conn.forget(fut)
            self._probe_failed(r, str(e))
            return
        ms = sw.ms()
        was_down = not r.up
        fresh: list[float] = []
        with r.lock:
            r.reported = dict(info) if isinstance(info, dict) else {}
            r.fails = 0
            r.up = True
            if self._on_ttft is not None:
                fresh = self._drain_ttft_locked(r)
        r.observe_probe_ms(ms, self.ewma_alpha)
        for sample_ms in fresh:
            try:
                self._on_ttft(sample_ms)
            except Exception:  # noqa: BLE001 — observer must not
                pass           # poison the probe loop
        if was_down:
            chaos.note_ok("gateway.probe", r.key)
            log.info("replica healthy", kv={"replica": r.key,
                                            "probe_ms": round(ms, 1)})
            self._on_change()

    def _drain_ttft_locked(self, r: Replica) -> list[float]:
        """NEW (seq > high-water) per-request TTFT samples from the
        replica's just-stored ``Info()``; caller holds ``r.lock``."""
        raw = r.reported.get("ttft_recent")
        if not isinstance(raw, (list, tuple)):
            return []
        pairs: list[tuple[int, float]] = []
        for item in raw:
            try:
                pairs.append((int(item[0]), float(item[1])))
            except Exception:  # noqa: BLE001 — any malformed item
                continue       # (wrong shape/type) is just skipped
        if pairs and max(s for s, _ in pairs) < r.ttft_seen:
            # Every reported seq is BELOW the high-water mark: the
            # replica restarted with a fresh ledger (same registry
            # key, seq counter back at 1). Reset, or its post-restart
            # samples would be dropped until the new seq caught up.
            r.ttft_seen = 0
        fresh: list[float] = []
        for seq, sample_ms in pairs:
            if seq > r.ttft_seen:
                r.ttft_seen = seq
                fresh.append(sample_ms)
        return fresh

    def _probe_failed(self, r: Replica, why: str) -> None:
        with r.lock:
            r.fails += 1
            evict = r.up and r.fails >= self.eviction_threshold
            if evict:
                r.up = False
        if evict:
            self._close_conn(r)
            log.warning("replica evicted",
                        kv={"replica": r.key, "fails": r.fails,
                            "err": why})
            self._on_change()

    def _ensure_conn(self, r: Replica):
        conn = r.conn
        if conn is not None and conn.healthy:
            return conn
        with r.lock:
            if r.dialing:
                return None  # a concurrent probe owns the re-dial
            r.dialing = True
        try:
            self._close_conn(r)
            try:
                conn = rpc_mod._dial(r.node, self.dial_timeout)
            except OSError:
                return None
            with r.lock:
                r.conn = conn
        finally:
            with r.lock:
                r.dialing = False
        if self._closed.is_set():
            # Lost the race with close(): its sweep may already have
            # run — never leave a live socket + reader thread behind.
            self._close_conn(r)
            return None
        return conn

    def _close_conn(self, r: Replica) -> None:
        with r.lock:
            conn, r.conn = r.conn, None
        if conn is not None:
            conn.close()

    # ------------------------------------------------------------ routing

    def _snapshot_replicas(self) -> list[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def healthy(self) -> list[Replica]:
        return [r for r in self._snapshot_replicas()
                if r.up and r.conn is not None and r.conn.healthy]

    def n_healthy(self) -> int:
        return len(self.healthy())

    def healthy_class(self, serve_class: str) -> list[Replica]:
        """Healthy replicas of one serving class (ISSUE 16). Falls
        back to ALL healthy replicas when none report the class —
        classes are advisory (every engine serves every endpoint), so
        a unified fleet keeps serving when the operator asks for a
        class it never deployed."""
        reps = self.healthy()
        cls = [r for r in reps if r.serve_class() == serve_class]
        return cls or reps

    def pick(self, affinity_key: str | None = None,
             exclude=(),
             serve_class: str | None = None,
             prefer_domain: int | None = None) -> Replica | None:
        """Route one request: affinity first (when sane), else least
        loaded. None when the fleet has no healthy replica.

        ``exclude`` (replica keys) steers a RE-route away from
        replicas that already failed this request — when every healthy
        replica has failed it, exclusion lapses (retrying someone
        beats shedding with survivors idle).

        ``serve_class`` (ISSUE 16) narrows to one serving class —
        softly, via :meth:`healthy_class`: the two-stage router's
        prefill/decode picks, degrading to the whole fleet when no
        replica reports the class.

        ``prefer_domain`` (ISSUE 18) is the locality preference: a
        replica in that topology domain beats any out-of-domain score
        (its KV/prefix traffic stays on the fast intra-domain leg),
        but never a replica that can't serve — draining and
        KV-exhausted still sort last, and a domain with no healthy
        member degrades to the whole fleet. Affinity hashing is
        likewise restricted to the in-domain stable set when one
        exists, so a key's pinned replica is local when it can be."""
        candidates = (self.healthy() if serve_class is None
                      else self.healthy_class(serve_class))
        if not candidates:
            return None
        if exclude:
            fresh = [r for r in candidates if r.key not in exclude]
            if fresh:
                candidates = fresh
        # A DRAINING replica (lifecycle, ISSUE 13) and an exhausted KV
        # pool (kv_free_blocks == 0) both sort LAST: any request
        # routed there earns a typed shed, so a replica that can
        # actually serve wins at any latency score; replicas that
        # report neither signal are unaffected.
        candidates.sort(key=lambda r: (r.lifecycle() == "draining",
                                       r.kv_free_blocks() == 0,
                                       prefer_domain is not None
                                       and r.domain() is not None
                                       and r.domain() != prefer_domain,
                                       r.score(), r.key))
        chosen = candidates[0]
        if affinity_key is not None and len(candidates) > 1:
            stable = sorted(candidates, key=lambda r: r.key)
            if prefer_domain is not None:
                local = [r for r in stable
                         if r.domain() == prefer_domain]
                if local:
                    stable = local
            pinned = stable[rpc_mod.fnv32a(affinity_key) % len(stable)]
            # Affinity yields to load: a warm prefix cache is worth a
            # bounded cost multiple, not a wedged replica. It also
            # yields when the pinned replica's KV block pool is
            # EXHAUSTED (kv_free_blocks == 0, the paged engine's
            # admission headroom): routing there earns a typed shed,
            # not a cache hit — a cold miss on a replica with room
            # strictly beats it.
            # ... and when the pinned replica is DRAINING: its warm
            # prefix cache is about to be freed anyway, and every
            # request routed there sheds.
            exhausted = (pinned.kv_free_blocks() == 0
                         or pinned.lifecycle() == "draining")
            if (not exhausted
                    and pinned.score()
                    <= chosen.score() * self.affinity_slack + 10.0):
                chosen = pinned
        f = chaos.hit("gateway.route", chosen.key)
        if f is not None:
            if f.action == "delay":
                f.sleep()
            elif f.action == "drop":
                rest = [r for r in candidates if r is not chosen]
                return rest[0] if rest else None
        return chosen

    def begin(self, r: Replica) -> None:
        with r.lock:
            r.inflight += 1
            r.calls += 1

    def done(self, r: Replica, ms: float | None = None,
             ok: bool = True) -> None:
        with r.lock:
            r.inflight = max(0, r.inflight - 1)
        if ok and ms is not None:
            r.observe_ms(ms, self.ewma_alpha)

    def fail(self, r: Replica, why: str = "") -> None:
        """A dispatch failed on transport: count it like a probe
        failure so repeated call failures evict without waiting for
        ``eviction_threshold`` probe rounds."""
        with r.lock:
            r.inflight = max(0, r.inflight - 1)
        self._probe_failed(r, why or "call transport failure")

    # --------------------------------------------------------- inspection

    def min_ewma_ms(self) -> float:
        obs = [r.ewma_ms for r in self.healthy() if r.ewma_ms > 0]
        return min(obs) if obs else 0.0

    def status(self) -> dict:
        reps = [r.snapshot() for r in self._snapshot_replicas()]
        return {"service": self.service,
                "replicas": sorted(reps, key=lambda d: d["key"]),
                "healthy": sum(1 for d in reps if d["up"])}

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        self._watch.cancel()
        # Join the loops (bounded) BEFORE sweeping connections: a
        # probe mid-dial could otherwise install a fresh conn (and its
        # reader thread) after the sweep — the wedged-thread leak the
        # chaos soak's teardown invariant exists to catch. A straggler
        # that outlives the join is covered by _ensure_conn's
        # closed-check, which discards its connection.
        for t in (self._probe_thread, self._watch_thread):
            if t is not threading.current_thread():
                t.join(timeout=self.dial_timeout + self.probe_timeout
                       + 2.0)
        for r in self._snapshot_replicas():
            self._close_conn(r)
