"""Inference gateway — the cluster frontdoor for generator fleets.

See :mod:`ptype_tpu.gateway.frontdoor` for the architecture overview,
docs/OPERATIONS.md "Serving at scale" for the runbook, and
``examples/serving/fleet.py`` for a runnable walkthrough.
"""

from ptype_tpu.errors import ShedError
from ptype_tpu.gateway.admission import AdmissionQueue
from ptype_tpu.gateway.directory import PrefixDirectory
from ptype_tpu.gateway.frontdoor import (GatewayActor, GatewayConfig,
                                         InferenceGateway,
                                         least_loaded_picker)
from ptype_tpu.gateway.pool import Replica, ReplicaPool
from ptype_tpu.gateway.slo import ScaleHint, SLOTracker

__all__ = [
    "AdmissionQueue",
    "GatewayActor",
    "GatewayConfig",
    "InferenceGateway",
    "PrefixDirectory",
    "Replica",
    "ReplicaPool",
    "ScaleHint",
    "SLOTracker",
    "ShedError",
    "least_loaded_picker",
]
