"""Global prefix directory: the fleet's KV cache as ONE index.

Disaggregated serving (ISSUE 16) makes the decode pick a cache-
placement decision: the router should land a migration on the decode
replica that already holds the prompt's prefix blocks, so the wire
ships only the divergent tail. Per-replica prefix caches answer "do
*I* hold this block"; this directory answers "who in the FLEET holds
it" — keyed by the same FNV-1a chain-hash family
(:func:`~ptype_tpu.serve_engine.blocks.block_hashes`) the pools dedup
with, so a directory hit and a pool hit are the same statement about
the same bytes.

Three contracts, each the fleet-level twin of a :class:`BlockPool`
invariant:

- **content-verified lookup** — a chain hash is 32 bits; the
  directory stores ``hash -> content`` per replica and a lookup
  whose content mismatches is a MISS, never a wrong route (the exact
  ``BlockPool.lookup`` collision contract).
- **eviction coherence** — a decode replica frees blocks under LRU
  pressure without telling anyone. Every replica exports a
  monotonic ``kv_evictions`` counter (``BlockPool.stats``); the
  router feeds the latest observed value through
  :meth:`note_evictions` BEFORE trusting the replica's entries, and
  any advance drops them all — conservative (the directory cannot
  know WHICH block the LRU reclaimed), so a stale entry can cost a
  re-send but never a mis-route.
- **death/restart coherence** — entries for a dead replica are
  harmless (the router only scores healthy candidates) and are
  reaped by :meth:`drop_replica` when the fleet watcher confirms the
  departure. A replica that RESTARTS under the same key comes back
  with a fresh pool and an eviction counter reset to 0 — observed as
  ``evictions < seen``, which also drops the stale entries (the same
  counter-went-backwards reset the pool's TTFT drain applies).

Everything here is advisory: the decode replica's ``MigratePlan``
re-verifies residency against its own pool (content-checked ref or
nothing), so a wrong directory answer degrades bandwidth, never
correctness.
"""

from __future__ import annotations

import collections

from ptype_tpu import lockcheck, logs

log = logs.get_logger("gateway.directory")


class PrefixDirectory:
    """``chain hash -> content`` per replica, bounded LRU per replica.

    ``max_blocks`` bounds each replica's entry count (oldest published
    first out) — the directory is a routing accelerator, not a mirror
    of every pool's full residency.
    """

    def __init__(self, max_blocks: int = 4096):
        self.max_blocks = int(max_blocks)
        self._lock = lockcheck.lock("gateway.directory")
        #: replica key -> OrderedDict[hash, content tuple] (LRU).
        self._blocks: dict[str, collections.OrderedDict] = {}
        #: replica key -> kv_evictions counter at last coherence check.
        self._seen_evictions: dict[str, int] = {}

    # ------------------------------------------------------------ publish

    def publish(self, replica: str, entries) -> int:
        """Record that ``replica`` holds ``entries`` — an iterable of
        ``(chain_hash, content)`` pairs (content: the block's token
        tuple, the pool's own verify key). Returns how many entries
        the replica now has."""
        with self._lock:
            d = self._blocks.setdefault(replica,
                                        collections.OrderedDict())
            for h, content in entries:
                h = int(h)
                d.pop(h, None)
                d[h] = tuple(int(t) for t in content)
            while len(d) > self.max_blocks:
                d.popitem(last=False)
            return len(d)

    # ---------------------------------------------------------- coherence

    def note_evictions(self, replica: str,
                       evictions: int | None) -> bool:
        """Feed the replica's latest reported ``kv_evictions``.
        Returns True when the counter moved (forward = LRU freed
        blocks; backward = the replica restarted with a fresh pool)
        and the replica's entries were dropped — the router must call
        this before trusting :meth:`holders`/:meth:`overlap` for the
        replica."""
        if evictions is None:
            return False
        evictions = int(evictions)
        with self._lock:
            seen = self._seen_evictions.get(replica)
            self._seen_evictions[replica] = evictions
            if seen is None or evictions == seen:
                return False
            dropped = self._blocks.pop(replica, None)
        log.info("prefix directory dropped replica entries",
                 kv={"replica": replica,
                     "entries": len(dropped or ()),
                     "evictions": evictions, "seen": seen,
                     "why": ("restart" if evictions < seen
                             else "lru eviction")})
        return True

    def drop_replica(self, replica: str) -> None:
        """The replica left the fleet: reap its entries (its state is
        gone with it; a restart re-publishes from scratch)."""
        with self._lock:
            self._blocks.pop(replica, None)
            self._seen_evictions.pop(replica, None)

    # ------------------------------------------------------------- lookup

    def holders(self, h: int, content) -> list[str]:
        """Replica keys holding the block — content-verified: a hash
        hit with different content is a collision and a MISS, the
        ``BlockPool.lookup`` contract fleet-wide."""
        want = tuple(int(t) for t in content)
        with self._lock:
            return sorted(
                r for r, d in self._blocks.items()
                if d.get(int(h)) == want)

    def overlap(self, replica: str, hashes, contents) -> int:
        """How many of the request's full blocks ``replica`` already
        holds (content-verified) — the decode-pick score."""
        with self._lock:
            d = self._blocks.get(replica)
            if not d:
                return 0
            n = 0
            for h, content in zip(hashes, contents):
                if d.get(int(h)) == tuple(int(t) for t in content):
                    n += 1
            return n

    # ---------------------------------------------------------- readouts

    def n_blocks(self, replica: str | None = None) -> int:
        with self._lock:
            if replica is not None:
                return len(self._blocks.get(replica, ()))
            return sum(len(d) for d in self._blocks.values())

    def stats(self) -> dict:
        with self._lock:
            return {"replicas": {r: len(d)
                                 for r, d in self._blocks.items()},
                    "blocks": sum(len(d)
                                  for d in self._blocks.values())}
