"""The inference gateway: one frontdoor for a fleet of generator actors.

``N`` :class:`~ptype_tpu.serve.GeneratorActor` replicas registered
under one service name are independent processes to the RPC plane; the
gateway turns them into ONE service (the Podracer shape — a frontdoor
that queues and dispatches while the accelerator engines stay
saturated; PAPERS.md, arxiv 2104.06272):

- requests pass **admission control** (bounded queue, per-request
  deadlines, SLO-aware shedding with typed
  :class:`~ptype_tpu.errors.ShedError` + retry-after) before any
  replica is touched;
- the **replica pool** routes each admitted request least-loaded (or
  prefix-affine), retries transport failures on surviving replicas
  within the deadline, and evicts/revives the dead;
- every outcome feeds the **SLO tracker**: p50/p95/p99, tokens/sec,
  shed rate, and a :meth:`scale_hint` the elastic layer can consume.

Deployment shapes:

- **library**: construct in the caller's process over any Registry
  (``InferenceGateway(cluster.registry)``), call
  :meth:`generate`/:meth:`call`;
- **service**: wrap in :class:`GatewayActor`, register it on an
  ActorServer under e.g. ``llm-gw`` — thin clients then speak plain
  actor RPC to the gateway tier, and sheds ride the wire typed
  (actor.py marshalling, rpc.py no-retry contract);
- **picker injection**: a process that must keep its plain
  :class:`~ptype_tpu.rpc.Client` can still route load-aware by
  plugging :func:`least_loaded_picker` into ``ConnConfig.picker``.
"""

from __future__ import annotations

import time
from concurrent.futures import TimeoutError as FuturesTimeoutError
from dataclasses import dataclass

import numpy as np

from ptype_tpu import chaos, logs, metrics as metrics_mod, retry, trace
from ptype_tpu.errors import (NoClientAvailableError, RemoteError, RPCError,
                              ShedError)
from ptype_tpu.gateway.admission import AdmissionQueue
from ptype_tpu.gateway.directory import PrefixDirectory
from ptype_tpu.gateway.pool import ReplicaPool
from ptype_tpu.gateway.slo import ScaleHint, SLOTracker, Stopwatch
from ptype_tpu.registry import Registry

log = logs.get_logger("gateway")


@dataclass
class GatewayConfig:
    """SLO and fleet knobs (docs/OPERATIONS.md "Serving at scale")."""

    #: Waiting-room bound; arrivals past it are shed with retry-after.
    max_queue_depth: int = 64
    #: Deadline applied when the caller passes none.
    default_deadline_s: float = 30.0
    #: Concurrent dispatches allowed per healthy replica. 1 matches the
    #: lock-serialized GeneratorActor; raise it for the batching /
    #: continuous engines, which turn concurrency into batch occupancy.
    per_replica_inflight: int = 1
    #: Active health probe cadence / budget (Info round-trips).
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 2.0
    #: Consecutive probe failures before a replica is evicted.
    eviction_threshold: int = 3
    #: EWMA weight for per-replica latency observations.
    ewma_alpha: float = 0.3
    #: Dial budget for (re)connecting to a replica.
    dial_timeout_s: float = 2.0
    #: Transport-failure re-routes allowed per request (each lands on a
    #: different replica when one exists; all bounded by the deadline).
    max_reroutes: int = 2
    #: Prefix-affinity: how many times costlier (estimated completion
    #: ms) the affine replica may be than the least-loaded choice
    #: before affinity yields to load.
    affinity_slack: float = 3.0
    #: Endpoint names on the replica actors.
    generate_method: str = "Generator.Generate"
    info_method: str = "Generator.Info"
    #: Optional p99 target feeding the scale hint (None = no SLO term).
    slo_p99_ms: float | None = None
    #: Optional TTFT p99 target (ms). Fed from replica-reported
    #: per-request samples (the serving ledger's ``ttft_recent``,
    #: drained by the pool's probes); a breach outranks the e2e p99
    #: term in the scale hint — prompt-heavy overload blows the first
    #: token long before the e2e tail moves.
    slo_ttft_p99_ms: float | None = None
    #: Rolling window for shed-rate / tokens-per-sec readouts.
    stats_window_s: float = 30.0
    #: Disaggregated serving (ISSUE 16): route single-row generates
    #: through the two-stage prefill→decode path — prefill-class pick
    #: fills the KV blocks, a decode-class pick (steered by the
    #: prefix directory) imports them over the quantized wire and
    #: owns the decode lifetime. Any migration failure falls back to
    #: plain Generate on the decode replica (local prefill): slower,
    #: never lost.
    disagg: bool = False
    #: KV wire encoding for migrations: ``q8`` (int8 + error-feedback
    #: residuals, ~4x less wire) or ``exact`` (raw dtype — the
    #: bit-exactness escape hatch parity tests pin against).
    kv_wire: str = "q8"
    #: Per-replica entry bound in the global prefix directory.
    directory_blocks: int = 4096
    #: Optional decode-side TPOT p99 target (ms) feeding the
    #: decode-class scale hint (prefill scales on queue/TTFT, decode
    #: on KV headroom and inter-token tail).
    slo_tpot_p99_ms: float | None = None
    #: This gateway's own topology domain (ISSUE 18,
    #: parallel/topology.py): the locality preference carried into
    #: every routing pick — replicas advertising the same domain win
    #: over out-of-domain scores, affinity hashes within the local
    #: stable set, and the per-class scale hints ask the reconciler
    #: to fill this domain first. None = topology-blind routing.
    domain: int | None = None


def _count_generated(result, stop_token: int) -> int:
    """Generated tokens in one ``Generate`` reply ``(B, max_new)``:
    each row ends at its first ``stop_token`` (inclusive — the engine
    emits it) or runs the full width; the pad tail after an early stop
    is NOT generated throughput."""
    arr = np.asarray(result)
    if arr.ndim != 2:
        return int(arr.size)
    if stop_token < 0:
        return int(arr.size)
    total = 0
    for row in arr:
        hits = np.flatnonzero(row == stop_token)
        total += (int(hits[0]) + 1) if hits.size else int(row.shape[0])
    return total


class InferenceGateway:
    """Admission → routing → dispatch for one generator service."""

    def __init__(self, registry: Registry, service: str = "llm",
                 cfg: GatewayConfig | None = None,
                 metrics_registry: metrics_mod.MetricsRegistry | None = None):
        self.cfg = cfg or GatewayConfig()
        self.service = service
        self.slo = SLOTracker(service, registry=metrics_registry,
                              window_s=self.cfg.stats_window_s,
                              slo_p99_ms=self.cfg.slo_p99_ms,
                              slo_ttft_p99_ms=self.cfg.slo_ttft_p99_ms,
                              slo_tpot_p99_ms=self.cfg.slo_tpot_p99_ms)
        self.pool = ReplicaPool(
            registry, service,
            info_method=self.cfg.info_method,
            probe_interval=self.cfg.probe_interval_s,
            probe_timeout=self.cfg.probe_timeout_s,
            eviction_threshold=self.cfg.eviction_threshold,
            ewma_alpha=self.cfg.ewma_alpha,
            dial_timeout=self.cfg.dial_timeout_s,
            affinity_slack=self.cfg.affinity_slack,
            on_change=self._on_fleet_change,
            on_ttft=self.slo.record_ttft)
        self.admission = AdmissionQueue(
            self.cfg.max_queue_depth,
            capacity=self._capacity,
            est_service_s=self.slo.est_service_s)
        #: Fleet-wide KV residency index (ISSUE 16): chain hash →
        #: holders, content-verified; steers the decode pick so shared
        #: prefixes migrate once and dedup after.
        self.directory = PrefixDirectory(self.cfg.directory_blocks)
        self._mreg = (metrics_registry if metrics_registry is not None
                      else metrics_mod.metrics)
        self._closed = False

    # ----------------------------------------------------------- capacity

    def _capacity(self) -> int:
        return max(1, self.pool.n_healthy()) * self.cfg.per_replica_inflight

    def _on_fleet_change(self) -> None:
        # Revived/arrived replicas may have grown capacity: grant
        # queued waiters now rather than at the next release(). The
        # pool's own construction fires this before the admission
        # queue exists — nothing can be waiting yet, so skipping is
        # correct, not a race.
        admission = getattr(self, "admission", None)
        if admission is not None:
            admission.poke()
        pool = getattr(self, "pool", None)
        if pool is not None:
            self.slo.g_replicas.set(pool.n_healthy())

    # ------------------------------------------------------------- public

    def generate(self, prompt, max_new_tokens: int = 16, *,
                 deadline_s: float | None = None,
                 affinity_key: str | None = None, **gen_kwargs):
        """The serving call: admit, route, dispatch, account.

        Raises :class:`ShedError` (typed, with ``retry_after_s``) when
        overloaded or out of deadline; :class:`RemoteError` when the
        replica's handler itself failed. Transport failures re-route to
        surviving replicas inside the deadline.

        With ``cfg.disagg`` set, eligible requests (single row, no
        kwargs the migration endpoints don't carry) take the two-stage
        prefill→migrate→decode path instead; everything else keeps the
        interleaved path unchanged.
        """
        if self.cfg.disagg and self._disagg_eligible(prompt,
                                                     gen_kwargs):
            return self._generate_disagg(
                prompt, int(max_new_tokens), deadline_s=deadline_s,
                affinity_key=affinity_key, **gen_kwargs)
        args = (prompt, int(max_new_tokens))
        stop_token = int(gen_kwargs.get("stop_token", -1))
        if gen_kwargs:
            # Positional tail matching GeneratorActor.Generate.
            order = ("temperature", "seed", "top_k", "top_p",
                     "stop_token", "pad_token", "repetition_penalty")
            defaults = {"temperature": 0.0, "seed": 0, "top_k": 0,
                        "top_p": 1.0, "stop_token": -1, "pad_token": 0,
                        "repetition_penalty": 1.0}
            unknown = set(gen_kwargs) - set(order)
            if unknown:
                raise TypeError(f"unknown generate kwargs: {unknown}")
            defaults.update(gen_kwargs)
            args = args + tuple(defaults[k] for k in order)
        return self.call(
            self.cfg.generate_method, *args,
            deadline_s=deadline_s, affinity_key=affinity_key,
            count_tokens=lambda out: _count_generated(out, stop_token))

    def call(self, method: str, *args,
             deadline_s: float | None = None,
             affinity_key: str | None = None,
             count_tokens=None):
        """Generic gateway dispatch (Generate is sugar over this).

        The whole request runs inside a ``gateway.request`` span with
        ``gateway.admit`` / ``gateway.route`` / ``rpc.call`` children —
        one stitched trace from frontdoor to replica handler (served as
        a GatewayActor, the span parents under the caller's actor RPC
        trace automatically)."""
        deadline = time.monotonic() + (deadline_s if deadline_s is not None
                                       else self.cfg.default_deadline_s)
        with trace.span("gateway.request", service=self.service,
                        method=method):
            self.slo.arrived()
            qsw = Stopwatch()
            try:
                with trace.span("gateway.admit"):
                    self.admission.admit(key=affinity_key or method,
                                         deadline=deadline)
            except ShedError:
                self.slo.shed()
                self._export_gauges()
                trace.maybe_dump(f"shed at admission ({self.service})")
                raise
            queue_ms = qsw.ms()
            try:
                return self._dispatch(method, args, deadline,
                                      affinity_key, count_tokens,
                                      queue_ms=queue_ms)
            finally:
                self.admission.release()
                self._export_gauges()

    def _dispatch(self, method: str, args, deadline: float,
                  affinity_key: str | None, count_tokens=None,
                  queue_ms: float = 0.0):
        last_err: Exception | None = None
        reroutes = 0
        tried: set[str] = set()
        route_ms = 0.0
        bo = retry.Backoff(base=0.05, cap=0.5)
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            rsw = Stopwatch()
            with trace.span("gateway.route") as rsp:
                r = self.pool.pick(affinity_key, exclude=tried,
                                   prefer_domain=self.cfg.domain)
                rsp.set_attr("replica", r.key if r is not None else None)
            route_ms += rsw.ms()
            if r is None:
                # Fleet momentarily empty (mass eviction / churn):
                # wait a beat for probes to revive someone — the
                # deadline bounds the patience.
                last_err = NoClientAvailableError(
                    f"no healthy replicas for {self.service!r}")
                bo.sleep(min(bo.next_delay(), max(0.0, remaining)))
                continue
            conn = r.conn
            if conn is None or not conn.healthy:
                continue
            self.pool.begin(r)
            rpc_sw = Stopwatch()
            fut = None
            # The dispatch span: the traceparent injected by
            # call_async is this span, so the replica's handler span
            # parents under the exact attempt that carried it (the
            # gateway bypasses Client's retry loop, where the rpc.call
            # span normally lives).
            dsp = trace.span("rpc.call", method=method, replica=r.key)
            try:
                with dsp:
                    fut = conn.call_async(method, args)
                    result = fut.result(timeout=remaining)
            except ShedError as e:
                # The REPLICA shed (paged-engine backlog / KV pool
                # exhausted — serve.admit). It is healthy and answered
                # typed: don't evict (pool.fail would count it toward
                # eviction), re-route to a sibling with headroom; when
                # every option sheds, propagate the replica's typed
                # shed with its retry hint intact. Skip the EWMA
                # sample (ms=None): a ~1 ms shed round-trip would
                # collapse the replica's latency score and the base
                # least-loaded pick would PREFER the exhausted replica
                # until the next probe refresh.
                self.pool.done(r, None, ok=True)
                last_err = e
                tried.add(r.key)
                reroutes += 1
                if reroutes > self.cfg.max_reroutes:
                    self.slo.shed()
                    trace.add_event("gateway.shed",
                                    last_error=str(e)[:200])
                    raise
                continue
            except RemoteError as e:
                # The replica RAN the handler and it raised: an
                # application error, not a routing problem. The replica
                # is healthy (it answered) — account and propagate.
                self.pool.done(r, rpc_sw.ms(), ok=True)
                self.slo.errored()
                raise e
            except FuturesTimeoutError:
                conn.forget(fut)
                self.pool.fail(r, "deadline expired in flight")
                last_err = RPCError(
                    f"call {method!r} exceeded its deadline on {r.key}")
                break  # remaining is spent; no budget to re-route
            except Exception as e:  # noqa: BLE001 — transport failure
                if fut is not None:
                    conn.forget(fut)
                self.pool.fail(r, str(e))
                last_err = e
                tried.add(r.key)
                reroutes += 1
                if reroutes > self.cfg.max_reroutes:
                    break
                continue
            ms = rpc_sw.ms()
            self.pool.done(r, ms, ok=True)
            # Real generated-token count (not B × max_new with the
            # pad tail charged as throughput): Generate supplies a
            # stop-token-aware counter; generic calls keep the shape
            # heuristic so tokens_per_sec never lies upward.
            tokens = 0
            try:
                if count_tokens is not None:
                    tokens = int(count_tokens(result))
                else:
                    tokens = int(result.shape[0]) * int(result.shape[1])
            except (AttributeError, IndexError, TypeError, ValueError):
                pass
            # Stage split (ISSUE 20): the interleaved path cannot see
            # inside the replica, so the whole service leg is one
            # "rpc" stage; queue-wait and route are the gateway's own.
            self.slo.answered(ms, tokens,
                              stages={"queue-wait": queue_ms,
                                      "route": route_ms, "rpc": ms})
            chaos.note_ok("gateway.call", r.key)
            # The dispatch rode the rpc transport: its success also
            # pairs rpc-class faults (the gateway bypasses Client's
            # retry loop, where that beacon normally lives).
            chaos.note_ok("rpc.call", method)
            return result
        # Out of deadline or out of re-routes: a typed shed, not a
        # timeout — the caller gets a retry hint and the request is
        # accounted, never silently lost.
        self.slo.shed()
        trace.add_event("gateway.shed", last_error=str(last_err)[:200])
        trace.maybe_dump(f"shed in dispatch ({self.service})")
        raise ShedError(
            f"request not served within its deadline "
            f"(last error: {last_err})",
            retry_after_s=self.slo.est_service_s())

    # ---------------------------------------- disaggregated (ISSUE 16)

    #: Generate kwargs the migration endpoints carry; the rest
    #: (pad_token, repetition_penalty) force the interleaved path
    #: unless left at their defaults.
    _DISAGG_KW = frozenset(("temperature", "seed", "top_k", "top_p",
                            "stop_token"))
    _DISAGG_KW_DEFAULTS = {"pad_token": 0, "repetition_penalty": 1.0}

    def _disagg_eligible(self, prompt, gen_kwargs) -> bool:
        """Single-row requests with migration-expressible kwargs ride
        the disaggregated path; everything else stays interleaved."""
        for k, v in gen_kwargs.items():
            if k in self._DISAGG_KW:
                continue
            if (k in self._DISAGG_KW_DEFAULTS
                    and v == self._DISAGG_KW_DEFAULTS[k]):
                continue
            return False
        try:
            arr = np.asarray(prompt)
        except Exception:  # noqa: BLE001 — let generate() raise it
            return False
        return arr.ndim == 2 and arr.shape[0] == 1

    def _mig_method(self, name: str) -> str:
        """Migration endpoint beside ``generate_method`` (same actor:
        ``Generator.Generate`` → ``Generator.<name>``)."""
        prefix = self.cfg.generate_method.rsplit(".", 1)[0]
        return f"{prefix}.{name}"

    def _rcall(self, r, method: str, args, deadline: float):
        """One TARGETED dispatch (no re-route — migration legs name
        their replica), with the same pool accounting and failure
        taxonomy as :meth:`_dispatch`: replica sheds and handler
        errors leave the replica healthy, transport failures feed
        eviction."""
        conn = r.conn
        if conn is None or not conn.healthy:
            raise RPCError(f"replica {r.key} not connected")
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise ShedError(
                f"out of deadline before {method!r} on {r.key}",
                retry_after_s=self.slo.est_service_s())
        self.pool.begin(r)
        sw = Stopwatch()
        fut = None
        try:
            with trace.span("rpc.call", method=method, replica=r.key):
                fut = conn.call_async(method, args)
                result = fut.result(timeout=remaining)
        except ShedError:
            self.pool.done(r, None, ok=True)
            raise
        except RemoteError:
            self.pool.done(r, sw.ms(), ok=True)
            raise
        except FuturesTimeoutError:
            conn.forget(fut)
            self.pool.fail(r, f"{method} exceeded deadline in flight")
            raise RPCError(
                f"call {method!r} exceeded its deadline on {r.key}")
        except Exception as e:  # noqa: BLE001 — transport failure
            if fut is not None:
                conn.forget(fut)
            self.pool.fail(r, str(e))
            raise
        self.pool.done(r, sw.ms(), ok=True)
        chaos.note_ok("rpc.call", method)
        return result

    def _generate_disagg(self, prompt, max_new: int, *,
                         deadline_s: float | None = None,
                         affinity_key: str | None = None,
                         **gen_kwargs):
        """The two-stage serving call: admit once, then prefill-pick →
        ``Prefill`` → decode-pick (prefix-directory-steered) →
        ``MigratePlan``/``ExportBlocks``/``ImportBlocks``/
        ``MigrateDecode``. Output is shaped and padded exactly like
        :meth:`generate`'s interleaved path."""
        gen = {"temperature": 0.0, "seed": 0, "top_k": 0,
               "top_p": 1.0, "stop_token": -1}
        gen.update({k: v for k, v in gen_kwargs.items() if k in gen})
        deadline = time.monotonic() + (deadline_s
                                       if deadline_s is not None
                                       else self.cfg.default_deadline_s)
        with trace.span("gateway.request", service=self.service,
                        method="disagg") as rq:
            self.slo.arrived()
            qsw = Stopwatch()
            try:
                with trace.span("gateway.admit"):
                    self.admission.admit(
                        key=affinity_key or "disagg",
                        deadline=deadline)
            except ShedError:
                self.slo.shed()
                self._export_gauges()
                trace.maybe_dump(f"shed at admission ({self.service})")
                raise
            queue_ms = qsw.ms()
            try:
                return self._dispatch_disagg(prompt, int(max_new),
                                             gen, deadline,
                                             affinity_key, rq,
                                             queue_ms)
            finally:
                self.admission.release()
                self._export_gauges()

    def _dispatch_disagg(self, prompt, max_new, gen, deadline,
                         affinity_key, rq, queue_ms=0.0):
        req_sw = Stopwatch()
        stages = {"queue-wait": queue_ms}
        stop_token = int(gen["stop_token"])
        counter = lambda out: _count_generated(out, stop_token)  # noqa: E731
        gen_args = (prompt, max_new, gen["temperature"], gen["seed"],
                    gen["top_k"], gen["top_p"], gen["stop_token"])
        mig_args = gen_args
        # ---- stage 1: prefill-class pick + Prefill
        rsw = Stopwatch()
        with trace.span("gateway.route", serve_class="prefill") as rsp:
            pre = self.pool.pick(affinity_key, serve_class="prefill",
                                 prefer_domain=self.cfg.domain)
            rsp.set_attr("replica", pre.key if pre is not None else None)
        stages["route"] = rsw.ms()
        if pre is None or pre.conn is None or not pre.conn.healthy:
            return self._dispatch(self.cfg.generate_method, gen_args,
                                  deadline, affinity_key, counter,
                                  queue_ms=queue_ms)
        # The request span names its replica pair and their topology
        # domains (ISSUE 20 satellite): before this, only the locality
        # counters recorded the split, so a stitched trace could not
        # show which domain pair served a slow request.
        rq.set_attr("prefill_replica", pre.key)
        rq.set_attr("prefill_domain", pre.domain())
        psw = Stopwatch()
        try:
            with trace.span("gateway.prefill", replica=pre.key):
                rep = self._rcall(pre, self._mig_method("Prefill"),
                                  (prompt, 1, gen["temperature"],
                                   gen["seed"], gen["top_k"],
                                   gen["top_p"], gen["stop_token"]),
                                  deadline)
        except Exception as e:  # noqa: BLE001 — shed, handler error,
            # or transport alike: Prefill never started owning
            # state, so a plain re-routed dispatch IS the recovery
            # (it accounts itself).
            log.info("disagg prefill failed; interleaved fallback",
                     kv={"replica": pre.key, "err": repr(e)[:200]})
            return self._dispatch(self.cfg.generate_method, gen_args,
                                  deadline, affinity_key, counter,
                                  queue_ms=queue_ms)
        stages["prefill"] = psw.ms()
        # Prefill returned the first token: the disagg path knows its
        # real per-request TTFT (goodput attribution, ISSUE 19).
        ttft_ms = req_sw.ms()
        export_id = rep["export_id"]
        first = int(rep["first_token"])
        bt = int(rep["block_tokens"])
        hashes = [int(h) for h in rep["hashes"]]
        toks = np.asarray(prompt)[0]
        contents = [tuple(int(t) for t in toks[i * bt:(i + 1) * bt])
                    for i in range(len(hashes))]
        if max_new <= 1 or (stop_token >= 0 and first == stop_token):
            # Decode budget spent inside prefill: no migration leg.
            self._release_export(pre, export_id)
            self.directory.publish(pre.key, zip(hashes, contents))
            out = np.zeros((1, max_new), np.int32)
            out[0, 0] = first
            self.slo.answered(req_sw.ms(), counter(out),
                              ttft_ms=ttft_ms, stages=stages)
            return out
        # ---- stage 2: decode-class pick, steered by the directory
        rsw = Stopwatch()
        with trace.span("gateway.route", serve_class="decode") as rsp:
            dec = self._pick_decode(pre, hashes, contents)
            rsp.set_attr("replica", dec.key if dec is not None else None)
        stages["route"] += rsw.ms()
        if dec is None:
            # One-replica fleet (or nothing else healthy): nowhere to
            # migrate — finish where the blocks already live.
            self._release_export(pre, export_id)
            return self._disagg_fallback(pre, gen_args, deadline,
                                         counter, req_sw)
        rq.set_attr("decode_replica", dec.key)
        rq.set_attr("decode_domain", dec.domain())
        # Locality ledger (ISSUE 18): every migration attempt counts
        # as intra- or cross-domain — the ``obs topo`` view and the
        # gateway drill's pressure assertion read these. Only when
        # both sides advertise a domain: a topology-blind fleet has
        # nothing meaningful to count.
        pre_dom, dec_dom = pre.domain(), dec.domain()
        if pre_dom is not None and dec_dom is not None:
            self._mreg.counter(
                "serve.migrate.local_domain" if dec_dom == pre_dom
                else "serve.migrate.cross_domain").add(1)
        ticket = None
        truncate = False
        msw = Stopwatch()  # migrate stage (and its trace span) open
        #                    BEFORE the chaos seam: an injected wire
        #                    delay is exactly what stage attribution —
        #                    histogram and waterfall alike — must catch.
        try:
            with trace.span("gateway.migrate", prefill=pre.key,
                            decode=dec.key) as msp:
                # The migration chaos seam: drop kills the transfer
                # outright, delay stalls it mid-flight, truncate
                # ships a wire missing blocks (the decode side
                # detects and refuses it) — every action lands on the
                # fallback path: local prefill on the decode replica,
                # correct tokens, never lost.
                f = chaos.hit("serve.migrate", dec.key)
                if f is not None:
                    if f.action == "drop":
                        raise RPCError("chaos: serve.migrate drop")
                    if f.action == "delay":
                        f.sleep()
                    elif f.action == "truncate":
                        truncate = True
                plan = self._rcall(dec,
                                   self._mig_method("MigratePlan"),
                                   mig_args, deadline)
                ticket = plan["ticket"]
                wire = self._rcall(
                    pre, self._mig_method("ExportBlocks"),
                    (export_id, plan["need"], self.cfg.kv_wire),
                    deadline)
                if truncate and wire.get("blocks"):
                    wire = dict(wire)
                    wire["blocks"] = wire["blocks"][:-1]
                imp = self._rcall(dec,
                                  self._mig_method("ImportBlocks"),
                                  (ticket, wire), deadline)
                msp.set_attr("blocks", len(wire.get("blocks", ())))
                msp.set_attr("bytes", int(imp.get("nbytes", 0)))
                msp.set_attr("resident", int(plan.get("resident", 0)))
            stages["migrate"] = msw.ms()
            self._release_export(pre, export_id)
            export_id = None
            dsw = Stopwatch()
            tokens = self._rcall(dec,
                                 self._mig_method("MigrateDecode"),
                                 (ticket, first), deadline)
            stages["decode"] = dsw.ms()
            ticket = None
        except ShedError:
            # The decode replica refused the plan typed (KV pool
            # exhausted / draining): nothing migrated, nothing owed —
            # unwind and re-route like any replica shed.
            if ticket is not None:
                self._abort_migration(dec, ticket)
            if export_id is not None:
                self._release_export(pre, export_id)
            trace.add_event("gateway.migrate_shed", decode=dec.key)
            return self._dispatch(self.cfg.generate_method, gen_args,
                                  deadline, affinity_key, counter)
        except Exception as e:  # noqa: BLE001 — any mid-transfer
            # failure (chaos drop/truncate, transport, handler): the
            # request falls back to LOCAL prefill on the decode
            # replica. Unwind first — the abort releases the decode
            # side's reservation so the fallback's own admission has
            # the blocks the plan was holding.
            log.info("migration failed; local-prefill fallback",
                     kv={"prefill": pre.key, "decode": dec.key,
                         "err": repr(e)[:200]})
            trace.add_event("gateway.migrate_failed",
                            decode=dec.key, err=str(e)[:200])
            if ticket is not None:
                self._abort_migration(dec, ticket)
            if export_id is not None:
                self._release_export(pre, export_id)
            out = self._disagg_fallback(dec, gen_args, deadline,
                                        counter, req_sw)
            # The decode replica prefilled locally: it now holds the
            # prompt's sealed blocks — publish them, and pair the
            # injected fault (the request completed; the seam
            # recovered by falling back).
            self.directory.publish(dec.key, zip(hashes, contents))
            chaos.note_ok("serve.migrate", dec.key)
            return out
        # ---- success: account, publish, pair the seam
        out = np.zeros((1, max_new), np.int32)
        emitted = [int(t) for t in tokens][:max_new]
        out[0, :len(emitted)] = emitted
        self.directory.publish(dec.key, zip(hashes, contents))
        e2e_ms = req_sw.ms()
        n_out = counter(out)
        self.slo.answered(e2e_ms, n_out, ttft_ms=ttft_ms,
                          tpot_ms=((e2e_ms - ttft_ms) / (n_out - 1)
                                   if n_out > 1 else None),
                          stages=stages)
        chaos.note_ok("serve.migrate", dec.key)
        chaos.note_ok("gateway.call", dec.key)
        return out

    def _pick_decode(self, pre, hashes, contents):
        """The decode pick: healthy decode-class replicas (minus the
        prefill pick), scored by content-verified directory overlap
        first (blocks NOT shipped), load second. Eviction counters
        are folded in before the directory is trusted — a replica
        whose pool churned drops its entries here, not after a
        mis-route.

        Locality (ISSUE 18): the migration wire rides the fast
        intra-domain leg only when the decode pick shares the prefill
        replica's topology domain — so when ANY in-domain candidate
        exists, out-of-domain ones (even directory holders) are
        dropped: re-shipping blocks inside the domain beats a
        cross-domain hit on the slow leg. A domain-blind fleet (no
        advertised domains) is unaffected."""
        cands = [r for r in self.pool.healthy_class("decode")
                 if r.key != pre.key
                 and r.conn is not None and r.conn.healthy
                 and r.lifecycle() != "draining"]
        if not cands:
            return None
        pre_dom = pre.domain()
        if pre_dom is not None:
            local = [r for r in cands if r.domain() == pre_dom]
            if local:
                cands = local
        for r in cands:
            self.directory.note_evictions(r.key, r.kv_evictions())
        best, best_ov = None, -1
        for r in sorted(cands, key=lambda r: (r.score(), r.key)):
            ov = self.directory.overlap(r.key, hashes, contents)
            if ov > best_ov:
                best, best_ov = r, ov
        return best

    def _disagg_fallback(self, dec, gen_args, deadline, counter,
                         req_sw):
        """Local prefill on the decode replica — the migration
        failure path. The replica re-prefills from the prompt (its
        prefix cache may still shortcut it) and owns the decode; only
        if IT fails too does the request re-enter the general
        re-routed dispatch."""
        if dec is not None and dec.conn is not None \
                and dec.conn.healthy:
            try:
                out = self._rcall(dec, self.cfg.generate_method,
                                  gen_args, deadline)
                self.slo.answered(req_sw.ms(), counter(out))
                return out
            except Exception as e:  # noqa: BLE001 — fall through to
                # the re-routed dispatch, which sheds typed if no one
                # can serve.
                log.info("decode-replica fallback failed; re-routing",
                         kv={"replica": dec.key,
                             "err": repr(e)[:200]})
        return self._dispatch(self.cfg.generate_method, gen_args,
                              deadline, None, counter)

    def _release_export(self, pre, export_id) -> None:
        """Best-effort: free the prefill side's parked blocks (they
        re-enter its LRU, still content-addressed for local reuse)."""
        try:
            self._rcall(pre, self._mig_method("ReleaseExport"),
                        (export_id,),
                        time.monotonic() + self.cfg.probe_timeout_s)
        except Exception:  # noqa: BLE001 — the engine's drained()
            # gate and Info() surface any leak; a failed release must
            # not fail the request.
            pass

    def _abort_migration(self, dec, ticket) -> None:
        """Best-effort: unwind the decode side's plan (derefs +
        reservation release + ledger retire as ``cancelled``)."""
        try:
            self._rcall(dec, self._mig_method("AbortMigration"),
                        (ticket,),
                        time.monotonic() + self.cfg.probe_timeout_s)
        except Exception:  # noqa: BLE001 — same contract as release
            pass

    def class_hint(self, serve_class: str) -> ScaleHint:
        """Per-class autoscale signal for a disaggregated fleet: the
        prefill pool scales on queue depth and the TTFT tail (prompt
        bursts), the decode pool on KV-block headroom and the TPOT
        tail (long decodes). Run one reconciler per class with
        ``hints=lambda: gw.class_hint("prefill")`` etc.; the combined
        :meth:`scale_hint` stays the unified-fleet signal."""
        reps = [r for r in self.pool.healthy()
                if r.serve_class() == serve_class]
        n = len(reps)
        queue = self.admission.depth
        inflight = sum(r.inflight for r in reps)
        signals = {"serve_class": serve_class, "n_replicas": n,
                   "queue_depth": queue, "inflight": inflight}
        # The domain dimension (ISSUE 18): per-domain replica counts
        # for this class, plus where the NEXT replica should land —
        # the reconciler passes ``spawn_domain`` to its launcher so
        # scale-ups fill the local domain before spilling across the
        # slow leg. Only when topology is in play (a configured
        # gateway domain or any advertising replica).
        doms: dict[str, int] = {}
        for r in reps:
            d = r.domain()
            if d is not None:
                doms[str(d)] = doms.get(str(d), 0) + 1
        if doms or self.cfg.domain is not None:
            signals["domains"] = doms
            signals["spawn_domain"] = self._spawn_domain(doms)
        if serve_class == "prefill":
            ttft = self.slo.h_ttft.percentile(99)
            signals["ttft_p99_ms"] = round(ttft, 2)
            if (self.cfg.max_queue_depth
                    and queue >= self.cfg.max_queue_depth // 2):
                return ScaleHint(1, "prefill queue above half depth",
                                 signals)
            if (self.cfg.slo_ttft_p99_ms is not None
                    and self.slo.h_ttft.count >= 20
                    and ttft > self.cfg.slo_ttft_p99_ms):
                return ScaleHint(
                    1, f"ttft p99 {ttft:.0f}ms over SLO "
                       f"{self.cfg.slo_ttft_p99_ms:.0f}ms", signals)
            if n > 1 and queue == 0 and inflight == 0:
                return ScaleHint(-1, "prefill pool idle", signals)
            return ScaleHint(0, "steady", signals)
        if serve_class == "decode":
            frees = [v for v in (r.kv_free_blocks() for r in reps)
                     if v is not None]
            signals["min_kv_free_blocks"] = (min(frees) if frees
                                             else None)
            tpots = [v for v in
                     (r.reported_float("tpot_p99_ms") for r in reps)
                     if v is not None]
            signals["tpot_p99_ms"] = (round(max(tpots), 2) if tpots
                                      else None)
            if frees and min(frees) == 0:
                return ScaleHint(1, "decode kv pool exhausted",
                                 signals)
            if (self.cfg.slo_tpot_p99_ms is not None and tpots
                    and max(tpots) > self.cfg.slo_tpot_p99_ms):
                return ScaleHint(
                    1, f"tpot p99 {max(tpots):.0f}ms over SLO "
                       f"{self.cfg.slo_tpot_p99_ms:.0f}ms", signals)
            if n > 1 and inflight == 0 and queue == 0:
                return ScaleHint(-1, "decode pool idle", signals)
            return ScaleHint(0, "steady", signals)
        return ScaleHint(0, f"unknown class {serve_class!r}", signals)

    def _spawn_domain(self, doms: dict[str, int]) -> int | None:
        """Where the next replica of a class should land: the
        gateway's own domain while it is no fuller than the emptiest
        populated domain ("fill the local domain first"), else the
        least-populated advertised domain (lowest ordinal on ties —
        deterministic, so repeated hints don't oscillate)."""
        local = self.cfg.domain
        if not doms:
            return local
        least = min(doms.values())
        if local is not None and doms.get(str(local), 0) <= least:
            return int(local)
        return min((int(k) for k, v in doms.items() if v == least))

    def disagg_hints(self) -> dict:
        """Both per-class hints at once (``GatewayActor.Info`` /
        operator surface)."""
        return {cls: self.class_hint(cls)
                for cls in ("prefill", "decode")}

    # --------------------------------------------------------- inspection

    def _export_gauges(self) -> None:
        self.slo.g_queue.set(self.admission.depth)
        self.slo.g_replicas.set(self.pool.n_healthy())

    def stats(self) -> dict:
        """One structured readout: SLO surface + fleet + queue — what
        ``GatewayActor.Info`` serves and the runbook reads."""
        hint = self.scale_hint()
        return {
            "service": self.service,
            "queue_depth": self.admission.depth,
            "inflight": self.admission.inflight,
            "capacity": self._capacity(),
            "admitted": self.admission.admitted,
            "shed": {"full": self.admission.shed_full,
                     "slo": self.admission.shed_slo,
                     "deadline": self.admission.shed_deadline},
            "latency": self.slo.percentiles(),
            "tokens_per_sec": round(self.slo.tokens_per_sec(), 1),
            "shed_rate": round(self.slo.shed_rate(), 4),
            "scale_hint": {"delta": hint.delta, "reason": hint.reason},
            "tail": self.slo.worst(),
            "pool": self.pool.status(),
        }

    def scale_hint(self):
        """The autoscale signal (gateway/slo.py): advisory fleet-size
        delta from queue depth, shed rate, tail latency, utilization."""
        return self.slo.scale_hint(
            queue_depth=self.admission.depth,
            max_depth=self.cfg.max_queue_depth,
            n_replicas=self.pool.n_healthy(),
            inflight=self.admission.inflight,
            capacity=self._capacity())

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.admission.close()
        self.pool.close()


class GatewayActor:
    """Actor-RPC face of a gateway: register on an ActorServer under
    e.g. ``llm-gw`` and thin clients get admission control, shedding
    and load-aware routing through plain ``client.call`` — ShedError
    rides the wire typed."""

    def __init__(self, gateway: InferenceGateway):
        self._gw = gateway

    def Generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, seed: int = 0,
                 top_k: int = 0, top_p: float = 1.0,
                 stop_token: int = -1, pad_token: int = 0,
                 repetition_penalty: float = 1.0,
                 affinity_key: str = ""):
        return self._gw.generate(
            prompt, max_new_tokens, temperature=float(temperature),
            seed=int(seed), top_k=int(top_k), top_p=float(top_p),
            stop_token=int(stop_token), pad_token=int(pad_token),
            repetition_penalty=float(repetition_penalty),
            affinity_key=str(affinity_key) or None)

    def Info(self) -> dict:
        return self._gw.stats()


def least_loaded_picker(pool: ReplicaPool):
    """A :class:`~ptype_tpu.rpc.ConnConfig` ``picker`` backed by a
    pool's load map: processes that keep a plain Client route to the
    least-loaded replica the pool knows about. Unknown connections (the
    pool hasn't probed that node) defer to round-robin by returning
    None."""

    def picker(conns):
        scores = {r.key: r.score() for r in pool.healthy()}
        best, best_score = None, None
        for c in conns:
            key = f"{c.node.address}:{c.node.port}"
            s = scores.get(key)
            if s is None:
                continue
            if best_score is None or s < best_score:
                best, best_score = c, s
        return best

    return picker
