"""The inference gateway: one frontdoor for a fleet of generator actors.

``N`` :class:`~ptype_tpu.serve.GeneratorActor` replicas registered
under one service name are independent processes to the RPC plane; the
gateway turns them into ONE service (the Podracer shape — a frontdoor
that queues and dispatches while the accelerator engines stay
saturated; PAPERS.md, arxiv 2104.06272):

- requests pass **admission control** (bounded queue, per-request
  deadlines, SLO-aware shedding with typed
  :class:`~ptype_tpu.errors.ShedError` + retry-after) before any
  replica is touched;
- the **replica pool** routes each admitted request least-loaded (or
  prefix-affine), retries transport failures on surviving replicas
  within the deadline, and evicts/revives the dead;
- every outcome feeds the **SLO tracker**: p50/p95/p99, tokens/sec,
  shed rate, and a :meth:`scale_hint` the elastic layer can consume.

Deployment shapes:

- **library**: construct in the caller's process over any Registry
  (``InferenceGateway(cluster.registry)``), call
  :meth:`generate`/:meth:`call`;
- **service**: wrap in :class:`GatewayActor`, register it on an
  ActorServer under e.g. ``llm-gw`` — thin clients then speak plain
  actor RPC to the gateway tier, and sheds ride the wire typed
  (actor.py marshalling, rpc.py no-retry contract);
- **picker injection**: a process that must keep its plain
  :class:`~ptype_tpu.rpc.Client` can still route load-aware by
  plugging :func:`least_loaded_picker` into ``ConnConfig.picker``.
"""

from __future__ import annotations

import time
from concurrent.futures import TimeoutError as FuturesTimeoutError
from dataclasses import dataclass

import numpy as np

from ptype_tpu import chaos, logs, metrics as metrics_mod, retry, trace
from ptype_tpu.errors import (NoClientAvailableError, RemoteError, RPCError,
                              ShedError)
from ptype_tpu.gateway.admission import AdmissionQueue
from ptype_tpu.gateway.pool import ReplicaPool
from ptype_tpu.gateway.slo import SLOTracker
from ptype_tpu.registry import Registry

log = logs.get_logger("gateway")


@dataclass
class GatewayConfig:
    """SLO and fleet knobs (docs/OPERATIONS.md "Serving at scale")."""

    #: Waiting-room bound; arrivals past it are shed with retry-after.
    max_queue_depth: int = 64
    #: Deadline applied when the caller passes none.
    default_deadline_s: float = 30.0
    #: Concurrent dispatches allowed per healthy replica. 1 matches the
    #: lock-serialized GeneratorActor; raise it for the batching /
    #: continuous engines, which turn concurrency into batch occupancy.
    per_replica_inflight: int = 1
    #: Active health probe cadence / budget (Info round-trips).
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 2.0
    #: Consecutive probe failures before a replica is evicted.
    eviction_threshold: int = 3
    #: EWMA weight for per-replica latency observations.
    ewma_alpha: float = 0.3
    #: Dial budget for (re)connecting to a replica.
    dial_timeout_s: float = 2.0
    #: Transport-failure re-routes allowed per request (each lands on a
    #: different replica when one exists; all bounded by the deadline).
    max_reroutes: int = 2
    #: Prefix-affinity: how many times costlier (estimated completion
    #: ms) the affine replica may be than the least-loaded choice
    #: before affinity yields to load.
    affinity_slack: float = 3.0
    #: Endpoint names on the replica actors.
    generate_method: str = "Generator.Generate"
    info_method: str = "Generator.Info"
    #: Optional p99 target feeding the scale hint (None = no SLO term).
    slo_p99_ms: float | None = None
    #: Optional TTFT p99 target (ms). Fed from replica-reported
    #: per-request samples (the serving ledger's ``ttft_recent``,
    #: drained by the pool's probes); a breach outranks the e2e p99
    #: term in the scale hint — prompt-heavy overload blows the first
    #: token long before the e2e tail moves.
    slo_ttft_p99_ms: float | None = None
    #: Rolling window for shed-rate / tokens-per-sec readouts.
    stats_window_s: float = 30.0


def _count_generated(result, stop_token: int) -> int:
    """Generated tokens in one ``Generate`` reply ``(B, max_new)``:
    each row ends at its first ``stop_token`` (inclusive — the engine
    emits it) or runs the full width; the pad tail after an early stop
    is NOT generated throughput."""
    arr = np.asarray(result)
    if arr.ndim != 2:
        return int(arr.size)
    if stop_token < 0:
        return int(arr.size)
    total = 0
    for row in arr:
        hits = np.flatnonzero(row == stop_token)
        total += (int(hits[0]) + 1) if hits.size else int(row.shape[0])
    return total


class InferenceGateway:
    """Admission → routing → dispatch for one generator service."""

    def __init__(self, registry: Registry, service: str = "llm",
                 cfg: GatewayConfig | None = None,
                 metrics_registry: metrics_mod.MetricsRegistry | None = None):
        self.cfg = cfg or GatewayConfig()
        self.service = service
        self.slo = SLOTracker(service, registry=metrics_registry,
                              window_s=self.cfg.stats_window_s,
                              slo_p99_ms=self.cfg.slo_p99_ms,
                              slo_ttft_p99_ms=self.cfg.slo_ttft_p99_ms)
        self.pool = ReplicaPool(
            registry, service,
            info_method=self.cfg.info_method,
            probe_interval=self.cfg.probe_interval_s,
            probe_timeout=self.cfg.probe_timeout_s,
            eviction_threshold=self.cfg.eviction_threshold,
            ewma_alpha=self.cfg.ewma_alpha,
            dial_timeout=self.cfg.dial_timeout_s,
            affinity_slack=self.cfg.affinity_slack,
            on_change=self._on_fleet_change,
            on_ttft=self.slo.record_ttft)
        self.admission = AdmissionQueue(
            self.cfg.max_queue_depth,
            capacity=self._capacity,
            est_service_s=self.slo.est_service_s)
        self._closed = False

    # ----------------------------------------------------------- capacity

    def _capacity(self) -> int:
        return max(1, self.pool.n_healthy()) * self.cfg.per_replica_inflight

    def _on_fleet_change(self) -> None:
        # Revived/arrived replicas may have grown capacity: grant
        # queued waiters now rather than at the next release(). The
        # pool's own construction fires this before the admission
        # queue exists — nothing can be waiting yet, so skipping is
        # correct, not a race.
        admission = getattr(self, "admission", None)
        if admission is not None:
            admission.poke()
        pool = getattr(self, "pool", None)
        if pool is not None:
            self.slo.g_replicas.set(pool.n_healthy())

    # ------------------------------------------------------------- public

    def generate(self, prompt, max_new_tokens: int = 16, *,
                 deadline_s: float | None = None,
                 affinity_key: str | None = None, **gen_kwargs):
        """The serving call: admit, route, dispatch, account.

        Raises :class:`ShedError` (typed, with ``retry_after_s``) when
        overloaded or out of deadline; :class:`RemoteError` when the
        replica's handler itself failed. Transport failures re-route to
        surviving replicas inside the deadline.
        """
        args = (prompt, int(max_new_tokens))
        stop_token = int(gen_kwargs.get("stop_token", -1))
        if gen_kwargs:
            # Positional tail matching GeneratorActor.Generate.
            order = ("temperature", "seed", "top_k", "top_p",
                     "stop_token", "pad_token", "repetition_penalty")
            defaults = {"temperature": 0.0, "seed": 0, "top_k": 0,
                        "top_p": 1.0, "stop_token": -1, "pad_token": 0,
                        "repetition_penalty": 1.0}
            unknown = set(gen_kwargs) - set(order)
            if unknown:
                raise TypeError(f"unknown generate kwargs: {unknown}")
            defaults.update(gen_kwargs)
            args = args + tuple(defaults[k] for k in order)
        return self.call(
            self.cfg.generate_method, *args,
            deadline_s=deadline_s, affinity_key=affinity_key,
            count_tokens=lambda out: _count_generated(out, stop_token))

    def call(self, method: str, *args,
             deadline_s: float | None = None,
             affinity_key: str | None = None,
             count_tokens=None):
        """Generic gateway dispatch (Generate is sugar over this).

        The whole request runs inside a ``gateway.request`` span with
        ``gateway.admit`` / ``gateway.route`` / ``rpc.call`` children —
        one stitched trace from frontdoor to replica handler (served as
        a GatewayActor, the span parents under the caller's actor RPC
        trace automatically)."""
        deadline = time.monotonic() + (deadline_s if deadline_s is not None
                                       else self.cfg.default_deadline_s)
        with trace.span("gateway.request", service=self.service,
                        method=method):
            self.slo.arrived()
            try:
                with trace.span("gateway.admit"):
                    self.admission.admit(key=affinity_key or method,
                                         deadline=deadline)
            except ShedError:
                self.slo.shed()
                self._export_gauges()
                trace.maybe_dump(f"shed at admission ({self.service})")
                raise
            try:
                return self._dispatch(method, args, deadline,
                                      affinity_key, count_tokens)
            finally:
                self.admission.release()
                self._export_gauges()

    def _dispatch(self, method: str, args, deadline: float,
                  affinity_key: str | None, count_tokens=None):
        last_err: Exception | None = None
        reroutes = 0
        tried: set[str] = set()
        bo = retry.Backoff(base=0.05, cap=0.5)
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            with trace.span("gateway.route") as rsp:
                r = self.pool.pick(affinity_key, exclude=tried)
                rsp.set_attr("replica", r.key if r is not None else None)
            if r is None:
                # Fleet momentarily empty (mass eviction / churn):
                # wait a beat for probes to revive someone — the
                # deadline bounds the patience.
                last_err = NoClientAvailableError(
                    f"no healthy replicas for {self.service!r}")
                bo.sleep(min(bo.next_delay(), max(0.0, remaining)))
                continue
            conn = r.conn
            if conn is None or not conn.healthy:
                continue
            self.pool.begin(r)
            t0 = time.perf_counter()
            fut = None
            # The dispatch span: the traceparent injected by
            # call_async is this span, so the replica's handler span
            # parents under the exact attempt that carried it (the
            # gateway bypasses Client's retry loop, where the rpc.call
            # span normally lives).
            dsp = trace.span("rpc.call", method=method, replica=r.key)
            try:
                with dsp:
                    fut = conn.call_async(method, args)
                    result = fut.result(timeout=remaining)
            except ShedError as e:
                # The REPLICA shed (paged-engine backlog / KV pool
                # exhausted — serve.admit). It is healthy and answered
                # typed: don't evict (pool.fail would count it toward
                # eviction), re-route to a sibling with headroom; when
                # every option sheds, propagate the replica's typed
                # shed with its retry hint intact. Skip the EWMA
                # sample (ms=None): a ~1 ms shed round-trip would
                # collapse the replica's latency score and the base
                # least-loaded pick would PREFER the exhausted replica
                # until the next probe refresh.
                self.pool.done(r, None, ok=True)
                last_err = e
                tried.add(r.key)
                reroutes += 1
                if reroutes > self.cfg.max_reroutes:
                    self.slo.shed()
                    trace.add_event("gateway.shed",
                                    last_error=str(e)[:200])
                    raise
                continue
            except RemoteError as e:
                # The replica RAN the handler and it raised: an
                # application error, not a routing problem. The replica
                # is healthy (it answered) — account and propagate.
                ms = (time.perf_counter() - t0) * 1000.0
                self.pool.done(r, ms, ok=True)
                self.slo.errored()
                raise e
            except FuturesTimeoutError:
                conn.forget(fut)
                self.pool.fail(r, "deadline expired in flight")
                last_err = RPCError(
                    f"call {method!r} exceeded its deadline on {r.key}")
                break  # remaining is spent; no budget to re-route
            except Exception as e:  # noqa: BLE001 — transport failure
                if fut is not None:
                    conn.forget(fut)
                self.pool.fail(r, str(e))
                last_err = e
                tried.add(r.key)
                reroutes += 1
                if reroutes > self.cfg.max_reroutes:
                    break
                continue
            ms = (time.perf_counter() - t0) * 1000.0
            self.pool.done(r, ms, ok=True)
            # Real generated-token count (not B × max_new with the
            # pad tail charged as throughput): Generate supplies a
            # stop-token-aware counter; generic calls keep the shape
            # heuristic so tokens_per_sec never lies upward.
            tokens = 0
            try:
                if count_tokens is not None:
                    tokens = int(count_tokens(result))
                else:
                    tokens = int(result.shape[0]) * int(result.shape[1])
            except (AttributeError, IndexError, TypeError, ValueError):
                pass
            self.slo.answered(ms, tokens)
            chaos.note_ok("gateway.call", r.key)
            # The dispatch rode the rpc transport: its success also
            # pairs rpc-class faults (the gateway bypasses Client's
            # retry loop, where that beacon normally lives).
            chaos.note_ok("rpc.call", method)
            return result
        # Out of deadline or out of re-routes: a typed shed, not a
        # timeout — the caller gets a retry hint and the request is
        # accounted, never silently lost.
        self.slo.shed()
        trace.add_event("gateway.shed", last_error=str(last_err)[:200])
        trace.maybe_dump(f"shed in dispatch ({self.service})")
        raise ShedError(
            f"request not served within its deadline "
            f"(last error: {last_err})",
            retry_after_s=self.slo.est_service_s())

    # --------------------------------------------------------- inspection

    def _export_gauges(self) -> None:
        self.slo.g_queue.set(self.admission.depth)
        self.slo.g_replicas.set(self.pool.n_healthy())

    def stats(self) -> dict:
        """One structured readout: SLO surface + fleet + queue — what
        ``GatewayActor.Info`` serves and the runbook reads."""
        hint = self.scale_hint()
        return {
            "service": self.service,
            "queue_depth": self.admission.depth,
            "inflight": self.admission.inflight,
            "capacity": self._capacity(),
            "admitted": self.admission.admitted,
            "shed": {"full": self.admission.shed_full,
                     "slo": self.admission.shed_slo,
                     "deadline": self.admission.shed_deadline},
            "latency": self.slo.percentiles(),
            "tokens_per_sec": round(self.slo.tokens_per_sec(), 1),
            "shed_rate": round(self.slo.shed_rate(), 4),
            "scale_hint": {"delta": hint.delta, "reason": hint.reason},
            "pool": self.pool.status(),
        }

    def scale_hint(self):
        """The autoscale signal (gateway/slo.py): advisory fleet-size
        delta from queue depth, shed rate, tail latency, utilization."""
        return self.slo.scale_hint(
            queue_depth=self.admission.depth,
            max_depth=self.cfg.max_queue_depth,
            n_replicas=self.pool.n_healthy(),
            inflight=self.admission.inflight,
            capacity=self._capacity())

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.admission.close()
        self.pool.close()


class GatewayActor:
    """Actor-RPC face of a gateway: register on an ActorServer under
    e.g. ``llm-gw`` and thin clients get admission control, shedding
    and load-aware routing through plain ``client.call`` — ShedError
    rides the wire typed."""

    def __init__(self, gateway: InferenceGateway):
        self._gw = gateway

    def Generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, seed: int = 0,
                 top_k: int = 0, top_p: float = 1.0,
                 stop_token: int = -1, pad_token: int = 0,
                 repetition_penalty: float = 1.0,
                 affinity_key: str = ""):
        return self._gw.generate(
            prompt, max_new_tokens, temperature=float(temperature),
            seed=int(seed), top_k=int(top_k), top_p=float(top_p),
            stop_token=int(stop_token), pad_token=int(pad_token),
            repetition_penalty=float(repetition_penalty),
            affinity_key=str(affinity_key) or None)

    def Info(self) -> dict:
        return self._gw.stats()


def least_loaded_picker(pool: ReplicaPool):
    """A :class:`~ptype_tpu.rpc.ConnConfig` ``picker`` backed by a
    pool's load map: processes that keep a plain Client route to the
    least-loaded replica the pool knows about. Unknown connections (the
    pool hasn't probed that node) defer to round-robin by returning
    None."""

    def picker(conns):
        scores = {r.key: r.score() for r in pool.healthy()}
        best, best_score = None, None
        for c in conns:
            key = f"{c.node.address}:{c.node.port}"
            s = scores.get(key)
            if s is None:
                continue
            if best_score is None or s < best_score:
                best, best_score = c, s
        return best

    return picker
