"""Jaxpr-level program audits — the dispatch-discipline contract.

ptlint's PT018–PT020 police the PYTHON around the hot programs;
:mod:`ptype_tpu.jitwatch` watches them recompile at runtime. This
module closes the middle: it TRACES a hot program (``jax.make_jaxpr``
— no execution, no backend compile) and asserts invariants of the
program itself, the ones a green test suite cannot see breaking:

- **no host callbacks** — a ``pure_callback``/``io_callback``/
  ``debug_callback`` (or ``debug.print``) inside a hot program turns
  every dispatch into a host round-trip; fine in a notebook, fatal in
  a decode loop;
- **no f64** — a ``convert_element_type`` to float64 (or any f64
  intermediate) doubles HBM and wire bytes for the whole downstream
  program, usually smuggled in by a dtype-less numpy literal (PT020's
  runtime shadow);
- **donation consumed** — ``donate_argnums`` is a *request*; whether
  XLA actually aliases the buffer only shows in the lowering
  (``tf.aliasing_output`` / ``jax.buffer_donor``). The engine's bank
  donation is what keeps the KV pool from being copied per step — a
  silently-dropped donation is a 2x HBM regression with no failing
  test;
- **collective-op count** — the bucketed collectives exist to make
  one bucket cost ONE launch; a refactor that un-fuses them (N psums
  for N leaves) keeps every parity test green and gives back the PR 1
  win. The audit counts collective primitives in the traced program
  and pins them to the bucket plan.

:func:`register` + :func:`audit_registered` keep a process-wide
registry of hot-program builders; :func:`register_default_programs`
installs the standing set (train-step grads, ZeRO shard-apply,
bucketed allreduce/reduce-scatter, the paged decode step, the fused
spec window) that ``tests/test_progaudit.py`` audits in the fast
tier.

Stdlib + jax only at the bottom; model/mesh imports live inside the
default builders (lazy — auditing a custom program must not drag the
transformer stack in).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax

__all__ = [
    "AuditError", "AuditReport", "audit", "collect_primitives",
    "register", "registered", "audit_registered", "audit_all",
    "register_default_programs", "DEFAULT_PROGRAMS",
]

#: Primitive names that round-trip through the host per dispatch.
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "debug_print",
})

#: Cross-device collective primitives (the launch-count currency).
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter",
})

#: Lowering markers that prove a donated invar was actually aliased
#: (or at least accepted as a donor) by XLA.
_DONATION_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")


class AuditError(AssertionError):
    """A hot program broke its dispatch contract; the message names
    every violated invariant."""


@dataclasses.dataclass
class AuditReport:
    """One audited program: counts, sites, and the verdict."""

    name: str
    problems: list[str]
    collectives: dict[str, int]
    callbacks: list[str]
    f64_sites: list[str]
    eqns: int
    donated_expected: int = 0
    donated_consumed: int = 0

    @property
    def ok(self) -> bool:
        return not self.problems

    def raise_if_failed(self) -> "AuditReport":
        if self.problems:
            raise AuditError(
                f"progaudit[{self.name}]: "
                + "; ".join(self.problems))
        return self

    def to_dict(self) -> dict:
        return {
            "name": self.name, "ok": self.ok,
            "problems": list(self.problems),
            "collectives": dict(self.collectives),
            "callbacks": list(self.callbacks),
            "f64_sites": list(self.f64_sites),
            "eqns": self.eqns,
            "donated_expected": self.donated_expected,
            "donated_consumed": self.donated_consumed,
        }


# ------------------------------------------------------------- traversal


def _sub_jaxprs(eqn):
    """Every nested jaxpr an equation carries (pjit/scan/shard_map →
    params['jaxpr']; cond → params['branches']; custom_*: call
    jaxprs)."""
    for v in eqn.params.values():
        if hasattr(v, "jaxpr"):        # ClosedJaxpr
            yield v.jaxpr
        elif hasattr(v, "eqns"):       # raw Jaxpr
            yield v
        elif isinstance(v, (list, tuple)):
            for b in v:
                if hasattr(b, "jaxpr"):
                    yield b.jaxpr
                elif hasattr(b, "eqns"):
                    yield b


def _walk_eqns(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from _walk_eqns(sub)


def collect_primitives(closed) -> dict[str, int]:
    """primitive name -> count over the whole (nested) jaxpr."""
    counts: dict[str, int] = {}
    for eqn in _walk_eqns(closed.jaxpr):
        counts[eqn.primitive.name] = counts.get(
            eqn.primitive.name, 0) + 1
    return counts


def _is_f64(aval) -> bool:
    dtype = getattr(aval, "dtype", None)
    return dtype is not None and str(dtype) == "float64"


# ----------------------------------------------------------------- audit


def audit(fn, args, *, name: str = "", donate_argnums=(),
          expect_collectives: int | dict | None = None,
          allow_f64: bool = False, static_argnums=(),
          check_donation: bool | None = None) -> AuditReport:
    """Trace ``fn(*args)`` (args may be ShapeDtypeStructs — nothing
    executes) and audit the program. ``expect_collectives``: an int
    pins the TOTAL collective-primitive count, a dict pins per-prim
    counts (prims absent from the dict are unconstrained). With
    ``donate_argnums`` the program is additionally LOWERED (still no
    execution) and the donation must survive into the lowering text.
    Returns the report; call :meth:`AuditReport.raise_if_failed` to
    turn problems into a typed :class:`AuditError`."""
    problems: list[str] = []
    closed = jax.make_jaxpr(fn, static_argnums=static_argnums)(*args)

    callbacks: list[str] = []
    f64_sites: list[str] = []
    collectives: dict[str, int] = {}
    n_eqns = 0
    for eqn in _walk_eqns(closed.jaxpr):
        n_eqns += 1
        prim = eqn.primitive.name
        if prim in CALLBACK_PRIMS or "callback" in prim:
            callbacks.append(prim)
        if prim in COLLECTIVE_PRIMS:
            collectives[prim] = collectives.get(prim, 0) + 1
        if prim == "convert_element_type" and _is_f64(
                eqn.outvars[0].aval):
            f64_sites.append("convert_element_type -> f64")
        else:
            for v in eqn.outvars:
                if _is_f64(getattr(v, "aval", None)):
                    f64_sites.append(f"{prim} produces f64")
                    break
    for v in closed.jaxpr.invars:
        if _is_f64(getattr(v, "aval", None)):
            f64_sites.append("f64 program input")

    if callbacks:
        problems.append(
            f"host callbacks in the program: {sorted(set(callbacks))} "
            f"(a host round-trip per dispatch)")
    if f64_sites and not allow_f64:
        problems.append(
            f"float64 in the program ({len(f64_sites)} sites, first: "
            f"{f64_sites[0]}) — 2x HBM/wire for every downstream op")
    if expect_collectives is not None:
        total = sum(collectives.values())
        if isinstance(expect_collectives, int):
            if total != expect_collectives:
                problems.append(
                    f"collective launch count {total} != expected "
                    f"{expect_collectives} (got {collectives}) — the "
                    f"bucket fusion contract")
        else:
            for prim, want in expect_collectives.items():
                got = collectives.get(prim, 0)
                if got != want:
                    problems.append(
                        f"{prim} count {got} != expected {want} "
                        f"(got {collectives})")

    donated_expected = donated_consumed = 0
    if check_donation is None:
        check_donation = bool(donate_argnums)
    if check_donation and donate_argnums:
        flat_args = []
        for i in donate_argnums:
            flat_args.extend(jax.tree_util.tree_leaves(args[i]))
        donated_expected = len(flat_args)
        lowered = jax.jit(
            fn, donate_argnums=donate_argnums,
            static_argnums=static_argnums).lower(*args)
        text = lowered.as_text()
        donated_consumed = sum(text.count(m) for m in
                               _DONATION_MARKERS)
        if donated_consumed < donated_expected:
            problems.append(
                f"donation not consumed in the lowering: "
                f"{donated_consumed}/{donated_expected} donated "
                f"buffers marked ({'/'.join(_DONATION_MARKERS)}) — "
                f"the banks are being COPIED per step")

    return AuditReport(
        name=name or getattr(fn, "__name__", "<fn>"),
        problems=problems, collectives=collectives,
        callbacks=sorted(set(callbacks)), f64_sites=f64_sites,
        eqns=n_eqns, donated_expected=donated_expected,
        donated_consumed=donated_consumed)


# -------------------------------------------------------------- registry

#: name -> zero-arg builder returning an :class:`AuditReport`.
_REGISTRY: dict[str, Callable[[], AuditReport]] = {}

DEFAULT_PROGRAMS = (
    "train.grads", "zero.shard_apply", "zero1.shard_apply",
    "zero2.grad_reduce_scatter", "zero3.param_gather",
    "zero3.shard_apply", "collectives.bucket_allreduce",
    "collectives.bucket_reduce_scatter",
    "collectives.hier_allreduce",
    "collectives.hier_reduce_scatter", "serve.decode_step",
    "serve.spec_window", "serve.kv_pack", "serve.kv_unpack",
)


def register(name: str, builder: Callable[[], AuditReport]) -> None:
    _REGISTRY[name] = builder


def registered() -> list[str]:
    return sorted(_REGISTRY)


def audit_registered(name: str) -> AuditReport:
    if name not in _REGISTRY:
        raise KeyError(f"no registered hot program {name!r} "
                       f"(have: {registered()})")
    return _REGISTRY[name]()


def audit_all(raise_on_failure: bool = False) -> dict[str, AuditReport]:
    out = {}
    for name in registered():
        out[name] = audit_registered(name)
        if raise_on_failure:
            out[name].raise_if_failed()
    return out


# ---------------------------------------------------- default programs


def _tiny_setup(preset: str):
    import jax.numpy as jnp

    from ptype_tpu.models import transformer as tfm

    cfg = tfm.preset(preset, dtype=jnp.float32)
    params_avals = jax.eval_shape(
        lambda r: tfm.init_params(r, cfg), jax.random.PRNGKey(0))
    return cfg, params_avals


def _build_train_grads(preset: str, batch: int, seq: int):
    def builder() -> AuditReport:
        import jax.numpy as jnp

        from ptype_tpu.models import transformer as tfm

        cfg, params_avals = _tiny_setup(preset)
        batch_avals = {
            "tokens": jax.ShapeDtypeStruct((batch, seq), jnp.int32),
            "targets": jax.ShapeDtypeStruct((batch, seq), jnp.int32)}

        def grads(p, b):
            return jax.value_and_grad(tfm.loss_fn)(p, b, cfg)

        # The single-replica grad program is collective-free (the
        # wire is the Store's job) and must stay f32/bf16 end to end.
        return audit(grads, (params_avals, batch_avals),
                     name="train.grads", expect_collectives=0)

    return builder


def _build_zero_apply():
    def builder() -> AuditReport:
        import jax.numpy as jnp

        from ptype_tpu.parallel import zero as zero_mod
        from ptype_tpu.parallel.mesh import build_mesh
        from ptype_tpu.parallel.topology import DATA_AXIS
        from ptype_tpu.train.trainer import default_optimizer_hparams

        n = jax.device_count()
        mesh = build_mesh({DATA_AXIS: n})
        shapes = ((4, 4), (8,))
        total = sum(1 if not s else int(__import__("math").prod(s))
                    for s in shapes)
        pad = (-total) % n
        elems = total + pad
        fn = zero_mod._shard_apply_fn(
            mesh, DATA_AXIS, shapes, "float32", pad,
            default_optimizer_hparams())
        f32 = jnp.float32
        avals = ([jax.ShapeDtypeStruct(s, f32) for s in shapes]
                 + [jax.ShapeDtypeStruct((elems,), f32)] * 4
                 + [jax.ShapeDtypeStruct((), jnp.int32),
                    jax.ShapeDtypeStruct((), f32)])
        # ONE all_gather: the fused shard-apply's whole point (pack →
        # slice my shard → AdamW → gather) — a second gather means
        # the fusion regressed to per-leaf assembly.
        return audit(fn, avals, name="zero.shard_apply",
                     expect_collectives={"all_gather": 1})

    return builder


def _build_zero1_apply():
    def builder() -> AuditReport:
        import jax.numpy as jnp

        from ptype_tpu.parallel import zero as zero_mod
        from ptype_tpu.parallel.mesh import build_mesh
        from ptype_tpu.parallel.topology import DATA_AXIS
        from ptype_tpu.train.trainer import default_optimizer_hparams

        n = jax.device_count()
        mesh = build_mesh({DATA_AXIS: n})
        shapes = ((4, 4), (8,))
        total = 24
        pad = (-total) % n
        elems = total + pad
        fn = zero_mod._shard_apply_full_fn(
            mesh, DATA_AXIS, shapes, "float32", pad,
            default_optimizer_hparams())
        f32 = jnp.float32
        avals = ([jax.ShapeDtypeStruct(s, f32) for s in shapes] * 2
                 + [jax.ShapeDtypeStruct((elems,), f32)] * 3
                 + [jax.ShapeDtypeStruct((), jnp.int32),
                    jax.ShapeDtypeStruct((), f32)])
        # The ZeRO-1 rung: full grads in, ONE param all_gather out —
        # same fusion contract as zero.shard_apply.
        return audit(fn, avals, name="zero1.shard_apply",
                     expect_collectives={"all_gather": 1})

    return builder


def _build_zero2_grad_rs():
    def builder() -> AuditReport:
        import jax.numpy as jnp

        from ptype_tpu.parallel import collectives as coll
        from ptype_tpu.parallel.mesh import build_mesh
        from ptype_tpu.parallel.topology import DATA_AXIS

        n = jax.device_count()
        mesh = build_mesh({DATA_AXIS: n})
        shapes = ((4, 4), (8,))
        pad = (-24) % n
        avals = [jax.ShapeDtypeStruct((n, *s), jnp.float32)
                 for s in shapes]
        fn = coll._bucket_reduce_scatter_fn(
            mesh, DATA_AXIS, "mean", shapes, "float32", pad, None,
            False, q_block=None)
        # ZeRO-2's whole point: grads arrive shard-resident from ONE
        # reduce_scatter per bucket and are NEVER allgathered — a
        # stray all_gather here silently rebuilds the full-grad
        # memory the rung exists to shed.
        return audit(fn, avals, name="zero2.grad_reduce_scatter",
                     expect_collectives={"reduce_scatter": 1,
                                         "all_gather": 0})

    return builder


def _build_zero3_gather():
    def builder() -> AuditReport:
        import jax.numpy as jnp

        from ptype_tpu.parallel import zero as zero_mod
        from ptype_tpu.parallel.mesh import build_mesh
        from ptype_tpu.parallel.topology import DATA_AXIS

        n = jax.device_count()
        mesh = build_mesh({DATA_AXIS: n})
        shapes = ((4, 4), (8,))
        total = 24
        pad = (-total) % n
        fn = zero_mod._bucket_gather_fn(mesh, DATA_AXIS, shapes,
                                        "float32", pad)
        aval = jax.ShapeDtypeStruct((total + pad,), jnp.float32)
        # The just-in-time param materialization: ONE all_gather per
        # bucket, however many leaves it unpacks to — per-leaf gathers
        # un-fuse the forward's dispatch overlap.
        return audit(fn, (aval,), name="zero3.param_gather",
                     expect_collectives={"all_gather": 1})

    return builder


def _build_zero3_apply():
    def builder() -> AuditReport:
        import jax.numpy as jnp

        from ptype_tpu.parallel import zero as zero_mod
        from ptype_tpu.train.trainer import default_optimizer_hparams

        n = jax.device_count()
        total = 24
        elems = total + (-total) % n
        fn = zero_mod._shard_apply3_fn(default_optimizer_hparams())
        f32 = jnp.float32
        flat = jax.ShapeDtypeStruct((elems,), f32)
        args = (flat, flat, flat, flat, flat,
                jax.ShapeDtypeStruct((), jnp.int32),
                jax.ShapeDtypeStruct((), f32))
        # ZeRO-3's update is purely elementwise on the resident flats
        # (the one all_gather lives in zero3.param_gather), and the
        # param/moment buffers are donated — a dropped donation
        # doubles the rung's resident footprint mid-step.
        return audit(fn, args, name="zero3.shard_apply",
                     donate_argnums=(0, 2, 3), expect_collectives=0)

    return builder


def _build_bucket_collective(kind: str):
    def builder() -> AuditReport:
        import jax.numpy as jnp

        from ptype_tpu.parallel import collectives as coll
        from ptype_tpu.parallel.mesh import build_mesh
        from ptype_tpu.parallel.topology import DATA_AXIS

        n = jax.device_count()
        mesh = build_mesh({DATA_AXIS: n})
        shapes = ((4, 4), (8,))
        pad = (-24) % n
        avals = [jax.ShapeDtypeStruct((n, *s), jnp.float32)
                 for s in shapes]
        if kind == "allreduce":
            fn = coll._bucket_all_reduce_fn(
                mesh, DATA_AXIS, "mean", shapes, "float32", pad, None,
                False, q_block=None)
            expect = {"psum": 1}
            name = "collectives.bucket_allreduce"
        else:
            fn = coll._bucket_reduce_scatter_fn(
                mesh, DATA_AXIS, "sum", shapes, "float32", pad, None,
                False, q_block=None)
            expect = {"reduce_scatter": 1}
            name = "collectives.bucket_reduce_scatter"
        # N leaves, ONE launch: the bucket contract PR 1 measured
        # 2-3x from; per-leaf regressions show up as count N.
        return audit(fn, avals, name=name, expect_collectives=expect)

    return builder


def _build_hier_collective(kind: str):
    def builder() -> AuditReport:
        import jax.numpy as jnp

        from ptype_tpu.parallel import collectives as coll
        from ptype_tpu.parallel.topology import Topology

        n = jax.device_count()
        no = 2 if n % 2 == 0 and n >= 4 else 1
        topo = Topology(n_outer=no, n_inner=n // no)
        mesh = topo.mesh()
        shapes = ((4, 4), (8,))
        pad = (-24) % n
        avals = [jax.ShapeDtypeStruct((n, *s), jnp.float32)
                 for s in shapes]
        if kind == "allreduce":
            fn = coll._hier_bucket_all_reduce_fn(
                mesh, "mean", shapes, "float32", pad,
                None, None, False, None, None)
            # The per-LEG launch pins (ISSUE 18): inner
            # reduce-scatter, ONE outer exchange (psum over the
            # slow leg — the only cross-domain launch), inner
            # allgather. An extra psum means a leg regressed to a
            # flat composite-axis collective and the slow-leg wire
            # win is gone while every parity test stays green.
            expect = ({"reduce_scatter": 1, "psum": 1,
                       "all_gather": 1} if topo.hierarchical
                      else None)
            name = "collectives.hier_allreduce"
        else:
            fn = coll._hier_bucket_reduce_scatter_fn(
                mesh, "sum", shapes, "float32", pad,
                None, None, False, None, None)
            # Two reduce-scatters (psum_scatter lowers to the
            # reduce_scatter primitive): inner then outer chunk.
            # No gather leg — ZeRO consumes the flat shard as-is.
            expect = ({"reduce_scatter": 2} if topo.hierarchical
                      else None)
            name = "collectives.hier_reduce_scatter"
        return audit(fn, avals, name=name, expect_collectives=expect)

    return builder


def _build_decode_step(preset: str, n_slots: int, n_blocks: int,
                       block_tokens: int):
    def builder() -> AuditReport:
        import jax.numpy as jnp

        from ptype_tpu.models import generate as gen

        cfg, params_avals = _tiny_setup(preset)
        B, nb, bt = n_slots, n_blocks, block_tokens
        kvh = cfg.n_kv_heads or cfg.n_heads
        hd = cfg.d_model // cfg.n_heads
        bank = jax.ShapeDtypeStruct(
            (cfg.n_layers, nb, bt, kvh, hd), jnp.float32)
        i32 = jnp.int32

        def step(params, kb, vb, tok, pos, tables, wr_b, wr_o):
            return gen.decode_step_paged(params, tok, pos, cfg, kb,
                                         vb, tables, wr_b, wr_o)

        args = (params_avals, bank, bank,
                jax.ShapeDtypeStruct((B,), i32),
                jax.ShapeDtypeStruct((B,), i32),
                jax.ShapeDtypeStruct((B, nb), i32),
                jax.ShapeDtypeStruct((B,), i32),
                jax.ShapeDtypeStruct((B,), i32))
        # Banks donated (the engine's donate_argnums=(2, 3) shape):
        # a dropped donation copies the whole KV pool every step.
        return audit(step, args, name="serve.decode_step",
                     donate_argnums=(1, 2), expect_collectives=0)

    return builder


def _build_spec_window(preset: str, k: int):
    def builder() -> AuditReport:
        import jax.numpy as jnp

        from ptype_tpu.models import generate as gen
        from ptype_tpu.models import transformer as tfm
        from ptype_tpu.serve_engine import (PagedGeneratorActor,
                                            SpecConfig)

        cfg = tfm.preset(preset, dtype=jnp.float32)
        params = jax.jit(lambda r: tfm.init_params(r, cfg))(
            jax.random.PRNGKey(0))
        dp, dcfg = gen.truncated_draft_params(params, cfg, n_layers=1)
        eng = PagedGeneratorActor(
            cfg, params=params, n_slots=2, block_tokens=16,
            spec=SpecConfig(draft_params=dp, draft_cfg=dcfg, k=k,
                            adaptive=False))
        try:
            W = k + 1
            B, nb = eng.n_slots, eng.nb
            i32, f32 = jnp.int32, jnp.float32
            kvh = cfg.n_kv_heads or cfg.n_heads
            hd = cfg.d_model // cfg.n_heads
            bank = jax.ShapeDtypeStruct(
                (cfg.n_layers, eng.pool.n_blocks, eng.block_tokens,
                 kvh, hd), f32)
            dbank = jax.ShapeDtypeStruct(
                (dcfg.n_layers, eng.pool.n_blocks, eng.block_tokens,
                 kvh, hd), f32)
            run = eng._window_prog(W, sampled=False)
            args = (
                params, dp,
                jax.ShapeDtypeStruct((B,), i32),        # tok
                jax.ShapeDtypeStruct((B,), i32),        # pos
                bank, bank, dbank, dbank,
                jax.ShapeDtypeStruct((B, nb), i32),     # tables
                jax.ShapeDtypeStruct((B, nb), i32),     # dtables
                jax.ShapeDtypeStruct((B,), i32),        # nalloc
                jax.ShapeDtypeStruct((B,), i32),        # dnalloc
                jax.ShapeDtypeStruct((B,), jnp.bool_),  # active
                jax.ShapeDtypeStruct((B, 2), jnp.uint32),  # keys
                jax.ShapeDtypeStruct((B,), i32),        # sctr
                jax.ShapeDtypeStruct((B,), f32),        # temps
                jax.ShapeDtypeStruct((B,), i32),        # topk
                jax.ShapeDtypeStruct((B,), f32),        # topp
            )
            # The REAL engine window program: fused draft scan +
            # batched verify + accept, both pools' banks donated,
            # ONE dispatch per window, no collectives, no f64.
            return audit(run, args, name="serve.spec_window",
                         donate_argnums=(4, 5, 6, 7),
                         expect_collectives=0)
        finally:
            eng.close()

    return builder


def _build_kv_pack(preset: str, n_blocks: int, block_tokens: int):
    def builder() -> AuditReport:
        import jax.numpy as jnp

        from ptype_tpu.models import transformer as tfm
        from ptype_tpu.serve_engine.migrate import make_pack_prog

        cfg = tfm.preset(preset, dtype=jnp.float32)
        kvh = cfg.n_kv_heads or cfg.n_heads
        hd = cfg.d_model // cfg.n_heads
        blk = jax.ShapeDtypeStruct(
            (cfg.n_layers, block_tokens, kvh, hd), jnp.float32)
        # Residuals donated (consumed into the pre-quantization sum,
        # replaced by the new per-block error): a dropped donation
        # doubles the wire path's live residual memory per transfer.
        return audit(make_pack_prog(), (blk, blk, blk, blk),
                     name="serve.kv_pack", donate_argnums=(2, 3),
                     expect_collectives=0)

    return builder


def _build_kv_unpack(preset: str, n_blocks: int, block_tokens: int):
    def builder() -> AuditReport:
        import jax.numpy as jnp

        from ptype_tpu.models import transformer as tfm
        from ptype_tpu.serve_engine.migrate import (make_pack_prog,
                                                    make_unpack_prog)

        cfg = tfm.preset(preset, dtype=jnp.float32)
        kvh = cfg.n_kv_heads or cfg.n_heads
        hd = cfg.d_model // cfg.n_heads
        shape = (cfg.n_layers, block_tokens, kvh, hd)
        blk = jax.ShapeDtypeStruct(shape, jnp.float32)
        # The wire avals come from the pack program itself, so the
        # audited unpack consumes exactly what pack emits.
        qk, sk, _, qv, sv, _ = jax.eval_shape(
            make_pack_prog(), blk, blk, blk, blk)
        bank = jax.ShapeDtypeStruct(
            (cfg.n_layers, n_blocks, block_tokens, kvh, hd),
            jnp.float32)
        args = (bank, bank,
                jax.ShapeDtypeStruct(qk.shape, qk.dtype),
                jax.ShapeDtypeStruct(sk.shape, sk.dtype),
                jax.ShapeDtypeStruct(qv.shape, qv.dtype),
                jax.ShapeDtypeStruct(sv.shape, sv.dtype),
                jax.ShapeDtypeStruct((), jnp.int32))
        # Banks donated (scatter-in-place): a dropped donation copies
        # the decode replica's WHOLE KV pool per imported block.
        return audit(make_unpack_prog(shape, jnp.float32), args,
                     name="serve.kv_unpack", donate_argnums=(0, 1),
                     expect_collectives=0)

    return builder


def register_default_programs(preset: str = "tiny", batch: int = 4,
                              seq: int = 16, spec_k: int = 3) -> None:
    """Install the standing hot-program registry (idempotent): the
    five program families the ROADMAP's perf wins live in. The
    fast-tier contract test audits every one; ``audit_all()`` is the
    operator surface."""
    register("train.grads", _build_train_grads(preset, batch, seq))
    register("zero.shard_apply", _build_zero_apply())
    register("zero1.shard_apply", _build_zero1_apply())
    register("zero2.grad_reduce_scatter", _build_zero2_grad_rs())
    register("zero3.param_gather", _build_zero3_gather())
    register("zero3.shard_apply", _build_zero3_apply())
    register("collectives.bucket_allreduce",
             _build_bucket_collective("allreduce"))
    register("collectives.bucket_reduce_scatter",
             _build_bucket_collective("reduce_scatter"))
    register("collectives.hier_allreduce",
             _build_hier_collective("allreduce"))
    register("collectives.hier_reduce_scatter",
             _build_hier_collective("reduce_scatter"))
    register("serve.decode_step",
             _build_decode_step(preset, n_slots=2, n_blocks=12,
                                block_tokens=16))
    register("serve.spec_window", _build_spec_window(preset, spec_k))
    register("serve.kv_pack",
             _build_kv_pack(preset, n_blocks=12, block_tokens=16))
    register("serve.kv_unpack",
             _build_kv_unpack(preset, n_blocks=12, block_tokens=16))
