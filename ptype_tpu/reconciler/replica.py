"""Replica lifecycle: the ONE home for serving-replica processes.

A serving replica is more than an actor behind a socket — it is a
lifecycle (ISSUE 13): **spawning** (process up, model building) →
**warm** (params loaded, server answering, NOT registered — the
standby pool's state: invisible to the gateway, one ``Activate`` away
from serving) → **active** (registered under the public service; the
gateway's watch stream routes to it) → **draining** (deregistration
pending: refuses new work typed, finishes in-flight) → **drained**
(deregistered, exiting). This module owns every transition:

- :class:`ReplicaHost` — builds the actor, serves it (the one
  sanctioned ``ActorServer`` construction for serving replicas — lint
  PT012), registers the ``Replica.*`` control endpoints, and runs the
  warm-up / activate / drain / exit machinery;
- :class:`ReplicaCtl` — the actor-RPC control face
  (``Replica.Status`` / ``Activate`` / ``Drain`` / ``Exit``) the
  reconciler drives cross-process;
- :class:`LocalLauncher` / :class:`ProcessLauncher` — how replicas
  come to exist: in-process (tests, drills, simulated fleets — real
  sockets, same control surface) or as real OS processes
  (``python -m ptype_tpu.reconciler.worker``, registered through the
  coordinator like any other cluster member);
- :class:`FakeGeneratorActor` — a numpy-only stand-in with the full
  drain surface, for control-plane tests and the scale bench.

Chaos seams: ``scale.spawn`` (``fail`` — the spawn dies before the
replica comes up; ``delay`` — slow spawn) fires in the launchers and
pairs with a ``note_ok`` once a spawned replica reports in;
``scale.drain`` (``wedge`` — hold the drain open past ``delay_s`` so
it blows its deadline and the reconciler's escalation path fires;
``delay``) fires in the drain worker and pairs when a drain (or its
escalation) completes.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

from ptype_tpu import lockcheck

from ptype_tpu import chaos, logs
from ptype_tpu import metrics as metrics_mod
from ptype_tpu import retry, rpc as rpc_mod
from ptype_tpu.actor import ActorServer
from ptype_tpu.errors import ClusterError, ShedError
from ptype_tpu.registry import Node, Registry
from ptype_tpu.serve import LIFECYCLE_CODES

log = logs.get_logger("reconciler.replica")


def serve_actor(actor, name: str = "Generator", host: str = "0.0.0.0",
                port: int = 0) -> ActorServer:
    """Construct + start the ActorServer for a serving replica — the
    sanctioned construction site outside :class:`ReplicaHost` (lint
    PT012: replica lifecycle has one home; the operator CLI's ``serve``
    command and ad-hoc fleets route through here)."""
    server = ActorServer(host, port)
    server.register(actor, name)
    server.serve()
    return server


class FakeGeneratorActor:
    """A model-free generator with the FULL lifecycle surface
    (Generate/Info/begin_drain/drained): control-plane tests and the
    scale bench exercise spawn/route/drain semantics without paying an
    XLA compile — the reconciler and gateway cannot tell."""

    def __init__(self, delay_s: float = 0.0, fill: int = 7):
        self.delay_s = float(delay_s)
        self.fill = int(fill)
        self.calls = 0
        self.lifecycle = "active"
        self._draining = False
        self._in_flight = 0
        self._lock = lockcheck.lock("reconciler.fake_actor")

    def Generate(self, prompt, max_new_tokens: int = 8, *args):
        import numpy as np

        # Gate + count under ONE lock (drained() reads under the same
        # lock): a request can never be past the gate yet invisible
        # to the drain — the TOCTOU the real actors also guard.
        with self._lock:
            if self._draining:
                raise ShedError("replica draining (scale-down in "
                                "progress); route elsewhere",
                                retry_after_s=0.05)
            self.calls += 1
            self._in_flight += 1
        try:
            if self.delay_s:
                time.sleep(self.delay_s)
            rows = np.asarray(prompt).shape[0]
            return np.full((rows, int(max_new_tokens)), self.fill,
                           np.int32)
        finally:
            with self._lock:
                self._in_flight -= 1

    def Info(self) -> dict:
        with self._lock:
            in_flight = self._in_flight
            calls = self.calls
        return {"in_flight": in_flight,
                "queue_depth": max(0, in_flight - 1),
                "calls": calls, "lifecycle": self.lifecycle}

    def begin_drain(self) -> None:
        with self._lock:
            self._draining = True
        self.lifecycle = "draining"

    def drained(self) -> bool:
        with self._lock:
            return self._draining and self._in_flight == 0


class ReplicaCtl:
    """Actor-RPC control face of a :class:`ReplicaHost` — what the
    reconciler drives across processes (``Replica.Status`` etc.)."""

    def __init__(self, host: "ReplicaHost"):
        self._host = host

    def Status(self) -> dict:
        return self._host.status()

    def Activate(self) -> dict:
        self._host.activate()
        return self._host.status()

    def Drain(self, deadline_s: float = 30.0) -> dict:
        self._host.drain(float(deadline_s))
        return self._host.status()

    def Exit(self) -> bool:
        self._host.request_exit()
        return True


class ReplicaHost:
    """One serving replica's whole lifecycle, in one object.

    Builds the actor (``actor_factory``), serves it + the control
    endpoints over one ActorServer, optionally warms it up
    (``warmup(actor)`` — e.g. compile a 1-token Generate so activation
    never pays a cold compile), and owns the registry registration:
    present exactly while the replica is active or draining-in-flight.
    """

    def __init__(self, registry: Registry, service: str,
                 node_name: str, actor_factory, warmup=None,
                 host: str = "127.0.0.1", port: int = 0,
                 generator_name: str = "Generator",
                 process_id: int = 0, warm_hold: bool = False,
                 metrics_registry=None, domain: int | None = None):
        self._registry = registry
        self.service = service
        self.node_name = node_name
        self.generator_name = generator_name
        self.process_id = int(process_id)
        #: Topology domain (the fast-ICI island this replica lives
        #: in, parallel/topology.py): advertised in the registration
        #: metadata so the gateway's locality-aware routing and the
        #: ``obs topo`` view see placement without a probe.
        self.domain = None if domain is None else int(domain)
        self._reg_handle = None
        self._reg_lock = lockcheck.lock("reconciler.replica.reg")
        self._exit = threading.Event()
        self._drain_thread: threading.Thread | None = None
        self._drain_started: float | None = None
        self._escalated = False
        self._mreg = (metrics_registry if metrics_registry is not None
                      else metrics_mod.metrics)
        if self.domain is not None:
            # Telemetry mirror of the registration metadata: the
            # ``obs topo`` view groups replicas by this gauge.
            self._mreg.gauge("serve.domain").set(float(self.domain))
        self._set_lifecycle("spawning")
        self.actor = actor_factory()
        self.server = serve_actor(self.actor, generator_name,
                                  host=host, port=port)
        self.server.register(ReplicaCtl(self), "Replica")
        self.host = host if host != "0.0.0.0" else self.server.host
        self.port = self.server.port
        if warmup is not None:
            warmup(self.actor)
        self._set_lifecycle("warm")
        log.info("replica host up",
                 kv={"service": service, "node": node_name,
                     "addr": f"{self.host}:{self.port}",
                     "warm_hold": warm_hold})
        if not warm_hold:
            self.activate()

    # ---------------------------------------------------------- lifecycle

    def _set_lifecycle(self, state: str) -> None:
        self.lifecycle = state
        actor = getattr(self, "actor", None)
        if actor is not None and state != "draining":
            # "draining" is the actor's own transition (begin_drain);
            # everything else is host-driven and mirrored onto the
            # actor so Info() reports it to the gateway's probes.
            try:
                actor.lifecycle = state
            except AttributeError:
                pass
        self._mreg.gauge("serve.lifecycle").set(
            LIFECYCLE_CODES.get(state, 2))

    @property
    def key(self) -> str:
        return f"{self.host}:{self.port}"

    def status(self) -> dict:
        info = {}
        try:
            info = self.actor.Info() or {}
        except Exception:  # noqa: BLE001 — status must always answer
            pass
        with self._reg_lock:
            registered = self._reg_handle is not None
        return {"service": self.service, "node": self.node_name,
                "addr": self.key, "lifecycle": self.lifecycle,
                "registered": registered,
                "in_flight": int(info.get("in_flight", 0) or 0),
                "queue_depth": int(info.get("queue_depth", 0) or 0),
                "drained": bool(self._actor_drained()),
                "drain_started": self._drain_started,
                "escalated": self._escalated}

    def activate(self) -> None:
        """warm → active: register under the public service name; the
        gateway's watch stream picks the replica up from here."""
        if self._exit.is_set():
            raise ClusterError("replica host is exiting")
        with self._reg_lock:
            if self._reg_handle is not None:
                return
            meta = {"lifecycle": "active"}
            if self.domain is not None:
                meta["domain"] = self.domain
            self._reg_handle = self._registry.register(
                self.service, self.node_name, self.host, self.port,
                process_id=self.process_id, metadata=meta)
        self._set_lifecycle("active")
        log.info("replica activated",
                 kv={"service": self.service, "node": self.node_name,
                     "addr": self.key})

    # -------------------------------------------------------------- drain

    def drain(self, deadline_s: float = 30.0) -> None:
        """active → draining → drained, in the zero-lost order: (1)
        stop admitting — the actor sheds new work typed and the
        frontdoor re-routes it, (2) finish in-flight, (3) deregister,
        (4) exit. The deadline is advisory here (the caller — the
        reconciler — owns escalation); past it the drain keeps trying
        so a late finish still loses nothing."""
        if self._drain_thread is not None or self._exit.is_set():
            return
        self._drain_started = time.monotonic()
        self._set_lifecycle("draining")
        begin = getattr(self.actor, "begin_drain", None)
        if callable(begin):
            begin()
        self._drain_thread = threading.Thread(
            target=self._drain_worker, args=(float(deadline_s),),
            name=f"drain-{self.node_name}", daemon=True)
        self._drain_thread.start()

    def _actor_drained(self) -> bool:
        fn = getattr(self.actor, "drained", None)
        if callable(fn):
            return bool(fn())
        try:
            return int((self.actor.Info() or {})
                       .get("in_flight", 0) or 0) == 0
        except Exception:  # noqa: BLE001 — a dead actor is drained
            return True

    def _drain_worker(self, deadline_s: float) -> None:
        # The scale.drain chaos seam: "wedge" holds the drain open for
        # delay_s (sized past the reconciler's deadline in drills, so
        # the escalation path fires); "delay" is a slow drain.
        hold_until = 0.0
        f = chaos.hit("scale.drain", self.node_name)
        if f is not None and f.action in ("wedge", "delay"):
            hold_until = time.monotonic() + f.delay_s
        while not self._exit.is_set():
            if self._actor_drained() and time.monotonic() >= hold_until:
                break
            self._exit.wait(0.02)
        if self._exit.is_set():
            return  # escalated / killed out from under the drain
        self.deregister()
        self._set_lifecycle("drained")
        chaos.note_ok("scale.drain", self.node_name)
        log.info("replica drained",
                 kv={"service": self.service, "node": self.node_name,
                     "wall_s": round(
                         time.monotonic() - self._drain_started, 3)})
        self.request_exit()

    def deregister(self) -> None:
        with self._reg_lock:
            handle, self._reg_handle = self._reg_handle, None
        if handle is not None:
            handle.close(revoke=True)

    # --------------------------------------------------------------- exit

    def request_exit(self) -> None:
        """Signal the host's owner (worker main loop / local handle)
        that this replica is done; idempotent."""
        self._exit.set()

    def wait_exit(self, timeout: float | None = None) -> bool:
        return self._exit.wait(timeout)

    @property
    def exiting(self) -> bool:
        return self._exit.is_set()

    def close(self) -> None:
        """Tear the replica down NOW (clean shutdown or escalation):
        deregister, close the server, stop the actor."""
        self._exit.set()
        self.deregister()
        self.server.close()
        close = getattr(self.actor, "close", None)
        if callable(close):
            try:
                close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        # Thread-hygiene (PT015 contract): the drain worker is
        # daemonized AND joined bounded — a host torn down mid-drain
        # must not leave a worker waking against closed sockets.
        t = self._drain_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)

    def kill(self) -> None:
        """Die the ungraceful way (drill stand-in for SIGKILL): the
        registration is revoked — the watch stream sees the loss like
        a lease expiry — and the sockets close mid-whatever."""
        self._escalated = True
        self.close()


# ------------------------------------------------------------- handles


class ReplicaHandle:
    """The reconciler's view of one replica it manages — a uniform
    face over in-process hosts and OS-process workers."""

    name: str
    addr: str

    def status(self) -> dict:
        raise NotImplementedError

    def activate(self) -> None:
        raise NotImplementedError

    def drain(self, deadline_s: float) -> None:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError

    def alive(self) -> bool:
        raise NotImplementedError

    @property
    def lifecycle(self) -> str:
        try:
            return str(self.status().get("lifecycle", "unknown"))
        except Exception:  # noqa: BLE001 — unreachable replica
            return "dead"


class LocalReplicaHandle(ReplicaHandle):
    """Handle over an in-process :class:`ReplicaHost`."""

    def __init__(self, host: ReplicaHost):
        self._host = host
        self.name = host.node_name
        self.addr = host.key

    def status(self) -> dict:
        return self._host.status()

    def activate(self) -> None:
        self._host.activate()

    def drain(self, deadline_s: float) -> None:
        self._host.drain(deadline_s)

    def kill(self) -> None:
        self._host.kill()

    def alive(self) -> bool:
        return not self._host.exiting

    def close(self) -> None:
        self._host.close()


class ProcessReplicaHandle(ReplicaHandle):
    """Handle over a worker OS process, driven via ``Replica.*``
    control RPCs on the worker's own actor server."""

    def __init__(self, name: str, host: str, port: int,
                 proc: subprocess.Popen, dial_timeout: float = 2.0,
                 call_timeout: float = 5.0):
        self.name = name
        self.addr = f"{host}:{port}"
        self._node = Node(address=host, port=int(port))
        self._proc = proc
        self._dial_timeout = float(dial_timeout)
        self._call_timeout = float(call_timeout)
        self._conn = None
        self._lock = lockcheck.lock("reconciler.proc_handle")

    def _call(self, method: str, *args):
        with self._lock:
            conn = self._conn
        if conn is None or not conn.healthy:
            # Dial OUTSIDE the lock: a wedged worker would otherwise
            # hold every concurrent control call (status polls, drain
            # orders) hostage for the full dial timeout. The install
            # is double-checked — a racer's healthy conn wins and the
            # loser's dial is closed, never leaked.
            dialed = rpc_mod._dial(self._node, self._dial_timeout)
            stale = None
            with self._lock:
                cur = self._conn
                if cur is not None and cur.healthy and cur is not conn:
                    conn, stale = cur, dialed  # lost the dial race
                else:
                    self._conn, conn, stale = dialed, dialed, cur
            if stale is not None and stale is not conn:
                stale.close()
        fut = conn.call_async(method, args)
        try:
            return fut.result(timeout=self._call_timeout)
        except Exception:
            conn.forget(fut)
            raise

    def status(self) -> dict:
        return self._call("Replica.Status")

    def activate(self) -> None:
        self._call("Replica.Activate")

    def drain(self, deadline_s: float) -> None:
        self._call("Replica.Drain", deadline_s)

    def exit(self) -> None:
        try:
            self._call("Replica.Exit")
        except Exception:  # noqa: BLE001 — already gone is fine
            pass

    def kill(self) -> None:
        with self._lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None
        if self._proc.poll() is None:
            self._proc.kill()
        try:
            # Reap: an escalated drain / replaced death must not leave
            # a zombie per event for the reconciler's lifetime.
            self._proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass

    def alive(self) -> bool:
        return self._proc.poll() is None

    def close(self) -> None:
        self.exit()
        try:
            self._proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self._proc.kill()
        with self._lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None


# ------------------------------------------------------------ launchers


def _spawn_fault(name: str) -> None:
    """The scale.spawn chaos seam, shared by both launchers."""
    f = chaos.hit("scale.spawn", name)
    if f is not None:
        if f.action == "delay":
            f.sleep()
        elif f.action == "fail":
            raise ClusterError(
                f"chaos: spawn of replica {name!r} failed")


class LocalLauncher:
    """Spawn replicas IN-PROCESS (real sockets, real registry, the
    full control surface — just no process isolation): the launcher
    for tests, chaos drills, and simulated fleets. The reconciler
    cannot tell it apart from :class:`ProcessLauncher`."""

    def __init__(self, registry: Registry, actor_factory,
                 warmup=None, service: str = "llm",
                 generator_name: str = "Generator",
                 metrics_registry=None, domain: int | None = None):
        self._registry = registry
        self._actor_factory = actor_factory
        self._warmup = warmup
        self._service = service
        self._generator_name = generator_name
        self._metrics_registry = metrics_registry
        #: Default topology domain for spawned replicas; a per-spawn
        #: ``domain=`` (the reconciler's placement hint) overrides it.
        self._domain = domain
        self.hosts: list[ReplicaHost] = []
        self._lock = lockcheck.lock("reconciler.launcher")

    def spawn(self, name: str, warm_hold: bool = False,
              domain: int | None = None) -> LocalReplicaHandle:
        _spawn_fault(name)
        host = ReplicaHost(
            self._registry, self._service, name,
            self._actor_factory, warmup=self._warmup,
            generator_name=self._generator_name, warm_hold=warm_hold,
            metrics_registry=self._metrics_registry,
            domain=domain if domain is not None else self._domain)
        with self._lock:
            self.hosts.append(host)
        chaos.note_ok("scale.spawn", name)
        return LocalReplicaHandle(host)

    def close(self) -> None:
        with self._lock:
            hosts, self.hosts = list(self.hosts), []
        for h in hosts:
            h.close()


class ProcessLauncher:
    """Spawn replicas as REAL OS processes: ``python -m
    ptype_tpu.reconciler.worker``, configured by environment, joined
    to the cluster through the coordinator address like any other
    member. The worker writes a ready file (host/port/pid) once its
    server answers; spawn blocks on it (bounded), then returns a
    control handle. Replica kind:

    - ``fake``  — :class:`FakeGeneratorActor` (control-plane drills);
    - ``paged`` — the real :class:`~ptype_tpu.serve_engine.engine.
      PagedGeneratorActor` over ``$PTYPE_REPLICA_PRESET``, warmed with
      a 1-token Generate so activation never pays the cold compile;
    - ``custom`` — ``factory="module:function"``: any actor (a
      trainer, an eval server) rides the same lifecycle.

    ``serve_class`` (disaggregated serving, ISSUE 16) stamps every
    worker this launcher spawns as ``"prefill"``, ``"decode"``, or
    the default ``"unified"`` — a per-class fleet is two launchers
    (one per class) each driven by its own reconciler off its own
    gateway hint (``InferenceGateway.class_hint``).

    Elastic training (ISSUE 17): a reconciler scaling a
    ``kind="custom"`` trainer fleet needs no extra plumbing into the
    training loop. Spawning or killing a worker changes registry
    membership; each survivor's ``FailureDetector`` reports the
    churn; the running step raises ``MembershipChanged``; and
    ``ElasticZeroTrainer.recover`` live-reshards the ZeRO state
    across the survivor set in place (``elastic.py``) — no restart,
    no checkpoint round trip.
    """

    def __init__(self, coordinator_address: str, service: str = "llm",
                 kind: str = "fake", preset: str = "tiny",
                 factory: str = "",
                 spawn_timeout_s: float = 60.0,
                 env: dict | None = None,
                 serve_class: str = "unified",
                 domain: int | None = None):
        self.coordinator_address = coordinator_address
        self.service = service
        self.kind = kind
        self.preset = preset
        #: ``module:function`` for ``kind="custom"`` (trainer or any
        #: other actor riding the same lifecycle).
        self.factory = factory
        self.serve_class = serve_class
        #: Default topology domain stamped on spawned workers
        #: (``PTYPE_REPLICA_DOMAIN``); per-spawn ``domain=`` wins.
        self.domain = domain
        self.spawn_timeout_s = float(spawn_timeout_s)
        self._env = dict(env or {})
        self.procs: list[subprocess.Popen] = []

    def spawn(self, name: str, warm_hold: bool = False,
              domain: int | None = None) -> ProcessReplicaHandle:
        # Reap + prune exited children first: a long-lived reconciler
        # cycles many workers, and the list must not grow (nor hold
        # zombies) one entry per drained/killed replica forever.
        self.procs = [p for p in self.procs if p.poll() is None]
        _spawn_fault(name)
        fd, ready = tempfile.mkstemp(prefix=f"replica-{name}-",
                                     suffix=".json")
        os.close(fd)
        os.unlink(ready)  # the worker creates it; absence = not ready
        env = {**os.environ, **self._env,
               "PTYPE_REPLICA_COORD": self.coordinator_address,
               "PTYPE_REPLICA_SERVICE": self.service,
               "PTYPE_REPLICA_NODE": name,
               "PTYPE_REPLICA_KIND": self.kind,
               "PTYPE_REPLICA_PRESET": self.preset,
               "PTYPE_REPLICA_FACTORY": self.factory,
               "PTYPE_REPLICA_WARM": "1" if warm_hold else "0",
               "PTYPE_REPLICA_SERVE_CLASS": self.serve_class,
               "PTYPE_REPLICA_READY_FILE": ready}
        dom = domain if domain is not None else self.domain
        if dom is not None:
            env["PTYPE_REPLICA_DOMAIN"] = str(int(dom))
        proc = subprocess.Popen(
            [sys.executable, "-m", "ptype_tpu.reconciler.worker"],
            env=env)
        self.procs.append(proc)
        bo = retry.Backoff(base=0.05, cap=0.5)
        deadline = time.monotonic() + self.spawn_timeout_s
        while True:
            if os.path.exists(ready):
                try:
                    with open(ready, encoding="utf-8") as f:
                        info = json.load(f)
                    break
                except (OSError, json.JSONDecodeError):
                    pass  # mid-write; next poll reads it whole
            if proc.poll() is not None:
                raise ClusterError(
                    f"replica worker {name!r} exited rc="
                    f"{proc.returncode} before reporting ready")
            if time.monotonic() > deadline:
                proc.kill()
                raise ClusterError(
                    f"replica worker {name!r} not ready within "
                    f"{self.spawn_timeout_s:g}s")
            bo.sleep()
        try:
            os.unlink(ready)
        except OSError:
            pass
        handle = ProcessReplicaHandle(name, info["host"],
                                      int(info["port"]), proc)
        chaos.note_ok("scale.spawn", name)
        return handle

    def close(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        self.procs = []
