"""The reconciler: desired-vs-actual replica count, closed-loop.

Everything upstream already exists — the gateway ranks a
:class:`~ptype_tpu.gateway.slo.ScaleHint` from shed rate / queue
depth / TTFT+e2e tails, ``health/rules.py`` pages on ``ttft-p99`` /
``kv-pressure`` / ``serve-stall``, the registry streams membership,
and the engine drains typed — this module is the loop that ACTS
(ROADMAP item 1): per tick it

1. refreshes the fleet view (registry watch via
   :class:`~ptype_tpu.elastic.FailureDetector` + the handles it owns),
2. folds the hint stream and any alert-derived votes through the
   :class:`~ptype_tpu.reconciler.policy.HysteresisPolicy` (cooldown +
   majority voting + min/max bounds — flapping hints cannot thrash),
3. REPLACES dead replicas (a registration lost without a drain the
   reconciler ordered = a death; actual fell below desired, so the
   gap respawns — the gateway's re-routes cover the survivors'
   in-flight in the meantime),
4. scales UP by activating a warm-standby first (params loaded,
   server answering, one ``Activate`` from serving — the fast path a
   spike needs) and spawning fresh replicas for the rest,
5. scales DOWN by draining the newest active replica it owns (stop
   admitting → finish in-flight → deregister → exit; zero lost), with
   a DEADLINE: a drain wedged past it is escalated — the replica is
   killed and the gateway's typed re-routes absorb the tail,
6. refills the warm pool.

Every decision lands three ways: a ``scale.*`` metrics series (the
sampler turns them into history; ``obs scale`` renders them), a
traced ``reconcile.*`` span (the flight recorder + Perfetto view),
and a KVLogger line — the loop is debuggable with the observability
planes that already exist.
"""

from __future__ import annotations

import inspect
import threading
import time
from dataclasses import dataclass

from ptype_tpu import chaos, lockcheck, logs
from ptype_tpu import metrics as metrics_mod
from ptype_tpu import trace
from ptype_tpu.elastic import FailureDetector
from ptype_tpu.reconciler.policy import HysteresisPolicy, ScaleDecision
from ptype_tpu.registry import Registry

log = logs.get_logger("reconciler")

#: Health-plane rules whose firing counts as a scale-up vote: the
#: pages that mean "serving capacity is the problem". ``slo-burn-rate``
#: is shed-driven, so its vote is URGENT (outranks down-votes, skips
#: the quorum) — the others vote like any other hint and still need
#: the window's majority.
SCALE_UP_RULES = ("ttft-p99", "kv-pressure", "serve-stall",
                  "slo-p99", "slo-burn-rate")
_URGENT_RULES = ("slo-burn-rate",)


@dataclass
class _AlertVote:
    """A ScaleHint-shaped vote synthesized from a health alert."""

    delta: int
    reason: str


@dataclass
class ReconcilerConfig:
    """Knobs (docs/OPERATIONS.md "Elastic serving")."""

    #: Fleet bounds: the availability floor and the budget ceiling.
    min_replicas: int = 1
    max_replicas: int = 8
    #: Warm standbys to keep (process up, params loaded, NOT
    #: registered): scale-up activates these instantly instead of
    #: paying a spawn. 0 = no warm pool.
    warm_pool: int = 0
    #: Hysteresis: at most one transition per cooldown window.
    cooldown_s: float = 30.0
    #: Voting window / quorum for non-urgent decisions.
    vote_window: int = 5
    vote_quorum: int = 3
    #: Reconcile cadence (run()'s tick interval).
    tick_interval_s: float = 1.0
    #: Drain budget before escalation (kill + let the gateway
    #: re-route): a wedged drain must not hold a scale-down hostage.
    drain_deadline_s: float = 30.0
    #: Bound on one spawn attempt (the launcher enforces its own).
    spawn_timeout_s: float = 60.0


class Reconciler:
    """The control loop over one service's replica fleet.

    ``launcher`` owns HOW replicas exist (LocalLauncher in-process,
    ProcessLauncher as real OS processes); ``hints`` is a callable
    returning the current :class:`ScaleHint` (in practice
    ``gateway.scale_hint`` — the reconciler polls it once per tick);
    health alerts arrive through :meth:`observe_alert` (wire it as an
    ``AlertEngine`` capture hook, or call it from the watch loop).
    ``tick()`` is synchronous and reentrant-free — tests drive it
    directly with a fake clock; ``run()``/``start()`` wrap it in the
    background cadence loop.
    """

    def __init__(self, registry: Registry, service: str, launcher,
                 hints=None, cfg: ReconcilerConfig | None = None,
                 policy: HysteresisPolicy | None = None,
                 metrics_registry=None):
        self.cfg = cfg or ReconcilerConfig()
        self.service = service
        self.launcher = launcher
        self._hints = hints
        self.policy = policy or HysteresisPolicy(
            min_replicas=self.cfg.min_replicas,
            max_replicas=self.cfg.max_replicas,
            cooldown_s=self.cfg.cooldown_s,
            window=self.cfg.vote_window,
            quorum=self.cfg.vote_quorum)
        self._reg = (metrics_registry if metrics_registry is not None
                     else metrics_mod.metrics)
        self._fd = FailureDetector(registry, service)
        self._fd.wait_seeded()
        self._lock = lockcheck.lock("reconciler.state")
        #: name -> handle, every replica this reconciler owns
        #: (warm + active + draining).
        self._handles: dict[str, object] = {}
        #: name -> escalation deadline (monotonic) for active drains.
        self._draining: dict[str, float] = {}
        #: addrs whose registry departure the reconciler ORDERED
        #: (drain complete / deliberate exit): losing them is not a
        #: death.
        self._expected_departures: set[str] = set()
        #: addrs whose HANDLE is known dead but whose registration
        #: has not expired yet: they must not count as serving
        #: capacity (a zombie lease is not a replica), or the
        #: replacement stalls up to a full lease TTL.
        self._dead_addrs: set[str] = set()
        #: names with a spawn thread in flight -> "active"|"warm".
        self._spawning: dict[str, str] = {}
        #: name -> the spawn Thread itself, for close()'s bounded
        #: join (daemonized AND joined — the PT015 contract).
        self._spawn_threads: dict[str, threading.Thread] = {}
        #: name -> last-read lifecycle. Refreshed ONCE per tick
        #: outside the main lock (for OS-process fleets a lifecycle
        #: read is a control RPC; a wedged worker must stall at most
        #: the refresh, never the lock observe_alert shares) and
        #: updated by spawn threads as their replica transitions.
        self._lc: dict[str, str] = {}
        #: Deaths awaiting a replacement: consumed (and counted as
        #: ``scale.replacements``) when a grow actually lands — never
        #: at death time, where no replacement exists yet.
        self._replace_credits = 0
        self._alert_votes: list[_AlertVote] = []
        #: Topology placement preference from the hint stream's
        #: ``spawn_domain`` signal (ISSUE 18): passed to
        #: ``launcher.spawn(domain=...)`` when the launcher takes it,
        #: so scale-ups fill the gateway's local domain first.
        self._spawn_domain: int | None = None
        self.desired: int | None = None
        self._seq = 0
        self._closed = threading.Event()
        self._thread: threading.Thread | None = None
        self._tick_lock = lockcheck.lock("reconciler.tick")

    # -------------------------------------------------------------- input

    def observe_alert(self, alert) -> None:
        """Health-plane firing → scale vote (rules → actions). Usable
        directly as an ``AlertEngine(capture=...)`` hook; rules
        outside :data:`SCALE_UP_RULES` are ignored, so wiring the
        whole engine through is safe."""
        rule = getattr(alert, "rule", "")
        if rule not in SCALE_UP_RULES:
            return
        reason = f"page:{rule}"
        if rule in _URGENT_RULES:
            reason += " (shedding over budget)"
        with self._lock:
            self._alert_votes.append(_AlertVote(delta=1, reason=reason))
        log.info("scale vote from health alert",
                 kv={"service": self.service, "rule": rule,
                     "node": getattr(alert, "node", "")})

    # ----------------------------------------------------------- the tick

    def tick(self, now: float | None = None) -> ScaleDecision | None:
        """One reconcile pass; returns the decision it applied (if
        any). Serialized — a slow spawn in a previous tick never
        overlaps state mutation with the next."""
        with self._tick_lock:
            return self._tick_locked(
                time.monotonic() if now is None else now)

    def _tick_locked(self, now: float) -> ScaleDecision | None:
        self._seq += 1
        self._refresh_lifecycles()
        self._note_deaths()
        self._prune_dead_handles()
        self._check_drains(now)
        actual = self._actual()
        if self.desired is None:
            self.desired = max(self.cfg.min_replicas, actual)
        decision = self._consume_votes(actual, now)
        if decision is not None:
            self._apply_decision(decision, actual)
        self._converge(now)
        self._refill_warm_pool()
        self._export(actual)
        return decision

    # ------------------------------------------------------- fleet view

    def _addr_handles(self) -> dict[str, object]:
        with self._lock:
            return {h.addr: h for h in self._handles.values()}

    def _refresh_lifecycles(self) -> None:
        """One status read per handle per tick, OUTSIDE the main
        lock. Every lock-held accounting section reads this cache —
        a wedged OS-process worker (status RPC blocking to its
        timeout) stalls at most this refresh, never the lock."""
        with self._lock:
            items = list(self._handles.items())
        cache = {}
        for name, h in items:
            cache[name] = h.lifecycle
        with self._lock:
            self._lc = cache

    def _actual(self) -> int:
        """Serving capacity now + capacity already committed: active
        registrations (mine and foreign) plus spawns in flight
        destined for active — counting the committed ones is what
        stops one hint from triggering a spawn per tick while the
        first spawn is still coming up. A replica whose spawn thread
        is still running counts ONLY as pending (never also as
        foreign/active — spawns are warm-held until the handle is
        installed, so it cannot be registry-visible before the
        reconciler owns it)."""
        mine = self._addr_handles()
        with self._lock:
            dead = set(self._dead_addrs)
        foreign = [n for n in self._fd.current()
                   if f"{n.address}:{n.port}" not in mine
                   and f"{n.address}:{n.port}" not in dead]
        with self._lock:
            active_mine = sum(
                1 for name in self._handles
                if name not in self._draining
                and name not in self._spawning
                and self._lc.get(name) == "active")
            pending = sum(1 for dest in self._spawning.values()
                          if dest == "active")
        return len(foreign) + active_mine + pending

    def _warm_handles(self) -> list:
        with self._lock:
            return [h for name, h in self._handles.items()
                    if name not in self._draining
                    and name not in self._spawning
                    and self._lc.get(name) == "warm"]

    def _note_deaths(self) -> None:
        lost, _joined = self._fd.drain_changes()
        if not lost:
            return
        mine = self._addr_handles()
        for addr in lost:
            with self._lock:
                expected = addr in self._expected_departures
                self._expected_departures.discard(addr)
                self._dead_addrs.discard(addr)  # registry caught up
            if expected:
                continue
            h = mine.get(addr)
            name = getattr(h, "name", addr)
            self._reg.counter("scale.deaths").add(1)
            log.warning("replica lost (not a reconciler-ordered "
                        "departure); will replace",
                        kv={"service": self.service, "replica": name,
                            "addr": addr})
            with trace.span("reconcile.replace", service=self.service,
                            replica=name, addr=addr):
                if h is not None:
                    try:
                        h.kill()  # reap the corpse (proc/server)
                    except Exception:  # noqa: BLE001 — already dead
                        pass
                    with self._lock:
                        self._handles.pop(name, None)
                        self._draining.pop(name, None)
            # actual is now below desired (if it isn't, _converge
            # zeroes the credit): the NEXT grow that lands consumes
            # this credit and counts as the replacement — never here,
            # where no replacement exists yet.
            with self._lock:
                self._replace_credits += 1

    def _prune_dead_handles(self) -> None:
        with self._lock:
            items = list(self._handles.items())
        for name, h in items:
            try:
                gone = not h.alive()
            except Exception:  # noqa: BLE001 — unreachable = gone
                gone = True
            if gone:
                with self._lock:
                    self._handles.pop(name, None)
                    was_draining = self._draining.pop(name, None)
                with self._lock:
                    was_active = self._lc.get(name) == "active"
                if (was_draining is None and was_active
                        and h.lifecycle not in ("drained", "dead")):
                    # An unexpected ACTIVE corpse: this IS the death,
                    # found via the handle before (or racing) the
                    # registry loss. Count it HERE and mark the
                    # departure expected, so whichever path sees the
                    # death first credits the replacement exactly
                    # once — otherwise a loss landing mid-tick (after
                    # _note_deaths, before _converge) reaps the
                    # handle creditless, _converge spawns an
                    # UNCREDITED replacement, and the next tick's
                    # credit is zeroed by actual >= desired: the
                    # replacement happened but was never counted.
                    # ACTIVE-only on purpose: a warm/spawning replica
                    # was never registered, so no loss event would
                    # ever clear these dedup entries — a stale entry
                    # at a reused addr would swallow a FUTURE real
                    # death as "expected" and leak forever.
                    with self._lock:
                        self._expected_departures.add(h.addr)
                        self._dead_addrs.add(h.addr)
                        self._replace_credits += 1
                    self._reg.counter("scale.deaths").add(1)
                    log.warning("replica handle dead outside a drain; "
                                "will replace",
                                kv={"service": self.service,
                                    "replica": name})
                elif was_draining is None and h.lifecycle not in (
                        "drained", "dead"):
                    # Warm/spawning corpse: reaped without death
                    # accounting — it held no registration and served
                    # no traffic; _refill_warm_pool replaces it.
                    log.warning("replica handle dead outside a drain",
                                kv={"service": self.service,
                                    "replica": name})

    # ------------------------------------------------------------ voting

    def _consume_votes(self, actual: int,
                       now: float) -> ScaleDecision | None:
        with self._lock:
            votes, self._alert_votes = self._alert_votes, []
        decision = None
        for v in votes:
            d = self.policy.observe(v, actual, now)
            decision = decision or d
        if self._hints is not None:
            try:
                hint = self._hints()
            except Exception as e:  # noqa: BLE001 — a broken hint
                # source must not kill the loop that replaces deaths.
                log.warning("hint source failed",
                            kv={"service": self.service,
                                "err": repr(e)})
                hint = None
            if hint is not None:
                self._note_spawn_domain(hint)
                d = self.policy.observe(hint, actual, now)
                decision = decision or d
        return decision

    def _note_spawn_domain(self, hint) -> None:
        """Fold the hint's placement signal (``signals["spawn_
        domain"]``, the gateway's fill-local-first choice). Sticky:
        a hint without the signal keeps the last preference rather
        than resetting placement to topology-blind mid-scale."""
        sig = getattr(hint, "signals", None)
        if not isinstance(sig, dict):
            return
        dom = sig.get("spawn_domain")
        if dom is None:
            return
        try:
            dom = int(dom)
        except (TypeError, ValueError):
            return
        with self._lock:
            self._spawn_domain = dom
        self._reg.gauge("scale.spawn_domain").set(float(dom))

    def _spawn_kwargs(self) -> dict:
        """Launcher spawn kwargs: always warm-held; plus the domain
        placement preference when one is known AND the launcher's
        spawn accepts it (launchers are duck-typed — a pre-topology
        launcher must keep working unchanged)."""
        kw: dict = {"warm_hold": True}
        with self._lock:
            dom = self._spawn_domain
        if dom is None:
            return kw
        try:
            params = inspect.signature(
                self.launcher.spawn).parameters
        except (TypeError, ValueError):
            return kw
        if "domain" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()):
            kw["domain"] = dom
        return kw

    def _apply_decision(self, decision: ScaleDecision,
                        actual: int) -> None:
        target = max(self.cfg.min_replicas,
                     min(self.cfg.max_replicas,
                         (self.desired or actual) + decision.delta))
        kind = "up" if decision.delta > 0 else "down"
        with trace.span(f"reconcile.scale_{kind}",
                        service=self.service, delta=decision.delta,
                        reason=decision.reason, desired=target,
                        actual=actual, urgent=decision.urgent):
            self.desired = target
        self._reg.counter("scale.decisions").add(1)
        self._reg.counter(f"scale.{kind}").add(1)
        log.info("scale decision",
                 kv={"service": self.service, "delta": decision.delta,
                     "desired": target, "actual": actual,
                     "reason": decision.reason,
                     "urgent": decision.urgent,
                     **{f"votes_{k}": v
                        for k, v in decision.votes.items()}})

    # --------------------------------------------------------- actuation

    def _converge(self, now: float) -> None:
        actual = self._actual()
        desired = self.desired or actual
        if actual >= desired:
            # No deficit: any death credits were for surplus capacity
            # nothing will (or should) replace — a later legitimate
            # scale-up must not be mislabeled a replacement.
            with self._lock:
                self._replace_credits = 0
        while actual < desired:
            if not self._grow_one():
                break
            actual = self._actual()
        # Shrink: drain the newest active replica the reconciler owns
        # (LIFO — the oldest replicas carry the warmest caches).
        # One drain ordered per tick: drains overlap tick boundaries
        # anyway, and sequential victims keep the in-flight surface
        # small if the hint reverses.
        if actual > desired:
            victim = self._pick_victim()
            if victim is not None:
                self._drain_one(victim, now)

    def _take_replace_credit(self) -> bool:
        with self._lock:
            if self._replace_credits > 0:
                self._replace_credits -= 1
                return True
        return False

    def _return_replace_credit(self, taken: bool) -> None:
        if taken:
            with self._lock:
                self._replace_credits += 1

    def _grow_one(self) -> bool:
        replacement = self._take_replace_credit()
        warm = self._warm_handles()
        if warm:
            h = warm[0]
            with trace.span("reconcile.activate",
                            service=self.service, replica=h.name,
                            replacement=replacement):
                try:
                    h.activate()
                except Exception as e:  # noqa: BLE001 — activation
                    # failure = the warm replica is broken: drop it.
                    log.warning("warm activation failed",
                                kv={"replica": h.name,
                                    "err": repr(e)})
                    with self._lock:
                        self._handles.pop(h.name, None)
                        self._lc.pop(h.name, None)
                    try:
                        h.kill()
                    except Exception:  # noqa: BLE001
                        pass
                    self._return_replace_credit(replacement)
                    return True  # retry loop: spawn instead
            with self._lock:
                self._lc[h.name] = "active"
            self._reg.counter("scale.activations").add(1)
            if replacement:
                self._reg.counter("scale.replacements").add(1)
            log.info("warm replica activated",
                     kv={"service": self.service, "replica": h.name,
                         "addr": h.addr, "replacement": replacement})
            return True
        return self._spawn_async("active", replacement=replacement)

    def _spawn_async(self, dest: str,
                     replacement: bool = False) -> bool:
        with self._lock:
            name = (f"{self.service}-r{self._seq}-"
                    f"{len(self._handles) + len(self._spawning)}")
            if name in self._spawning or name in self._handles:
                self._return_replace_credit(replacement)
                return False
            self._spawning[name] = dest

        def run():
            installed = False
            try:
                with trace.span("reconcile.spawn",
                                service=self.service, replica=name,
                                dest=dest, replacement=replacement):
                    # Spawn WARM always — the worker must not
                    # register itself before the reconciler holds its
                    # handle (a registry-visible, handle-less replica
                    # would double-count as foreign + pending and
                    # could trigger a spurious drain). Activation is
                    # the reconciler's move, after the handle lands.
                    h = self.launcher.spawn(name,
                                            **self._spawn_kwargs())
                self._reg.counter("scale.spawns").add(1)
                with self._lock:
                    self._handles[name] = h
                    self._lc[name] = "warm"
                installed = True
                if dest == "active":
                    h.activate()
                    with self._lock:
                        self._lc[name] = "active"
                if replacement:
                    self._reg.counter("scale.replacements").add(1)
                log.info("replica spawned",
                         kv={"service": self.service, "replica": name,
                             "addr": h.addr, "dest": dest,
                             "replacement": replacement})
            except Exception as e:  # noqa: BLE001 — spawn failures
                # are expected under chaos; the next tick retries.
                self._reg.counter("scale.spawn_failures").add(1)
                self._return_replace_credit(replacement)
                broken = None
                if installed:
                    # Activation failed after install: the replica is
                    # up but broken — drop and kill it.
                    with self._lock:
                        broken = self._handles.pop(name, None)
                        self._lc.pop(name, None)
                if broken is not None:
                    try:
                        broken.kill()
                    except Exception:  # noqa: BLE001 — best effort
                        pass
                log.warning("replica spawn failed",
                            kv={"service": self.service,
                                "replica": name, "err": repr(e)})
            finally:
                with self._lock:
                    self._spawning.pop(name, None)
                    self._spawn_threads.pop(name, None)

        t = threading.Thread(target=run, name=f"spawn-{name}",
                             daemon=True)
        with self._lock:
            self._spawn_threads[name] = t
        t.start()
        return True

    def _pick_victim(self):
        with self._lock:
            active = [(name, h) for name, h in self._handles.items()
                      if name not in self._draining
                      and name not in self._spawning
                      and self._lc.get(name) == "active"]
        if not active:
            return None  # only foreign replicas left: not ours to drain
        return active[-1][1]

    def _drain_one(self, h, now: float) -> None:
        with trace.span("reconcile.drain", service=self.service,
                        replica=h.name,
                        deadline_s=self.cfg.drain_deadline_s):
            with self._lock:
                self._draining[h.name] = (now
                                          + self.cfg.drain_deadline_s)
                self._expected_departures.add(h.addr)
            try:
                h.drain(self.cfg.drain_deadline_s)
            except Exception as e:  # noqa: BLE001 — an unreachable
                # victim is handled as a wedged drain (escalation).
                log.warning("drain order failed",
                            kv={"replica": h.name, "err": repr(e)})
        self._reg.counter("scale.drains").add(1)
        log.info("replica draining",
                 kv={"service": self.service, "replica": h.name,
                     "addr": h.addr,
                     "deadline_s": self.cfg.drain_deadline_s})

    def _check_drains(self, now: float) -> None:
        with self._lock:
            draining = list(self._draining.items())
        for name, deadline in draining:
            with self._lock:
                h = self._handles.get(name)
            if h is None:
                with self._lock:
                    self._draining.pop(name, None)
                continue
            lc = h.lifecycle
            if lc in ("drained", "dead") or not h.alive():
                with self._lock:
                    self._draining.pop(name, None)
                    self._handles.pop(name, None)
                close = getattr(h, "close", None)
                if callable(close):
                    try:
                        close()
                    except Exception:  # noqa: BLE001 — best effort
                        pass
                log.info("drain complete",
                         kv={"service": self.service, "replica": name})
            elif now > deadline:
                # Escalation: the drain wedged past its budget. Kill
                # the replica — its registration vanishes, the
                # gateway re-routes any tail it was still holding
                # (typed, within caller deadlines), and the fleet
                # reaches the desired size NOW instead of never.
                with trace.span("reconcile.escalate",
                                service=self.service, replica=name):
                    try:
                        h.kill()
                    except Exception:  # noqa: BLE001 — already gone
                        pass
                with self._lock:
                    self._draining.pop(name, None)
                    self._handles.pop(name, None)
                self._reg.counter("scale.drain_escalations").add(1)
                chaos.note_ok("scale.drain", name)
                log.warning("drain escalated past deadline; replica "
                            "killed",
                            kv={"service": self.service,
                                "replica": name})

    def _refill_warm_pool(self) -> None:
        if self.cfg.warm_pool <= 0:
            return
        with self._lock:
            warm = sum(1 for name in self._handles
                       if name not in self._draining
                       and name not in self._spawning
                       and self._lc.get(name) == "warm")
            pending = sum(1 for d in self._spawning.values()
                          if d == "warm")
        while warm + pending < self.cfg.warm_pool:
            if not self._spawn_async("warm"):
                break
            pending += 1

    # ------------------------------------------------------------- export

    def _export(self, actual: int) -> None:
        with self._lock:
            warm = sum(1 for name in self._handles
                       if name not in self._draining
                       and name not in self._spawning
                       and self._lc.get(name) == "warm")
            draining = len(self._draining)
            pending = len(self._spawning)
        self._reg.gauge("scale.desired").set(self.desired or 0)
        self._reg.gauge("scale.actual").set(actual)
        self._reg.gauge("scale.warm").set(warm)
        self._reg.gauge("scale.draining").set(draining)
        self._reg.gauge("scale.pending_spawns").set(pending)

    def status(self) -> dict:
        """One structured readout (``obs scale`` renders the metric
        twin of this; tests and the runbook read it directly)."""
        with self._lock:
            handles = {name: {"addr": h.addr,
                              "lifecycle": self._lc.get(name,
                                                        "unknown"),
                              "draining": name in self._draining}
                       for name, h in self._handles.items()}
            pending = dict(self._spawning)
        return {"service": self.service, "desired": self.desired,
                "actual": self._actual(),
                "replicas": handles, "pending_spawns": pending,
                "in_cooldown": self.policy.in_cooldown(
                    time.monotonic())}

    # --------------------------------------------------------------- run

    def start(self) -> "Reconciler":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run_loop, name=f"reconciler-{self.service}",
            daemon=True)
        self._thread.start()
        return self

    def _run_loop(self) -> None:
        while not self._closed.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop that
                # replaces dead replicas must not die of one bad tick.
                log.warning("reconcile tick failed",
                            kv={"service": self.service,
                                "err": repr(e)})
            self._closed.wait(self.cfg.tick_interval_s)

    def close(self, stop_fleet: bool = False) -> None:
        """Stop the loop (the fleet keeps serving unless
        ``stop_fleet`` — the reconciler is a controller, not the
        fleet's lifeline)."""
        self._closed.set()
        if self._thread is not None:
            self._thread.join(timeout=self.cfg.tick_interval_s + 5)
        # Bounded join of in-flight spawn threads (PT015 contract):
        # a spawn mid-flight at close is daemonized, but a test
        # tearing the reconciler down must not leak a worker that
        # wakes later against a dead registry. ONE shared deadline
        # across all of them — k wedged spawns must not stack k full
        # timeouts — and a registered-but-not-yet-started thread
        # (ident is None: the tick thread was preempted between
        # install and start) is skipped, not joined (joining an
        # unstarted thread raises out of close()).
        with self._lock:
            spawns = list(self._spawn_threads.values())
        deadline = time.monotonic() + self.cfg.spawn_timeout_s
        for t in spawns:
            if t.ident is None or t is threading.current_thread():
                continue
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        self._fd.close()
        if stop_fleet:
            with self._lock:
                handles = list(self._handles.values())
                self._handles.clear()
                self._draining.clear()
            for h in handles:
                close = getattr(h, "close", None)
                try:
                    (close or h.kill)()
                except Exception:  # noqa: BLE001 — teardown
                    pass
