"""Replica worker: the OS process a :class:`ProcessLauncher` spawns.

``python -m ptype_tpu.reconciler.worker`` reads its whole
configuration from the environment (the multiprocess-worker idiom the
chaos plan already uses — ``PTYPE_CHAOS_PLAN`` arms faults here with
zero code changes):

========================== ==========================================
``PTYPE_REPLICA_COORD``    coordinator address (host:port) to join
``PTYPE_REPLICA_SERVICE``  public service name (default ``llm``)
``PTYPE_REPLICA_NODE``     this replica's node name
``PTYPE_REPLICA_KIND``     ``fake`` | ``paged`` | ``custom``
                           (default ``paged``)
``PTYPE_REPLICA_PRESET``   model preset for ``paged`` (default tiny)
``PTYPE_REPLICA_FACTORY``  for ``custom``: ``module:function`` whose
                           call builds the actor — trainer replicas
                           and future engines ride the same
                           lifecycle with zero worker changes
                           (an optional ``warmup`` attribute on the
                           function is the warm-up hook)
``PTYPE_REPLICA_SERVE_CLASS`` ``unified`` | ``prefill`` | ``decode``

``PTYPE_REPLICA_DOMAIN``   topology domain ordinal (optional) —
                           advertised in the registration metadata for
                           the gateway's locality-aware routing
                           — the disaggregated-serving role stamped
                           on a ``paged`` engine (ISSUE 16); the
                           gateway's two-stage router reads it back
                           from ``Info()``
``PTYPE_REPLICA_WARM``     ``1`` = hold warm (spawn + load params +
                           compile, but do NOT register — the
                           standby-pool state; the reconciler's
                           ``Replica.Activate`` registers it later)
``PTYPE_REPLICA_READY_FILE`` path the worker writes
                           ``{"host","port","pid"}`` to once its
                           server answers — the spawn handshake
========================== ==========================================

The worker serves ``Generator.*`` plus the ``Replica.*`` control
endpoints and then parks until the host's exit event fires (drain
complete, ``Replica.Exit``, or SIGTERM), deregistering on the way
out. Lifecycle — spawn, warm-up, activate, drain, exit — lives
entirely in :class:`~ptype_tpu.reconciler.replica.ReplicaHost`; this
file is only the process skin around it.
"""

from __future__ import annotations

import json
import os
import signal

from ptype_tpu import logs

log = logs.get_logger("reconciler.worker")


def _actor_factory(kind: str, preset: str):
    if kind == "fake":
        from ptype_tpu.reconciler.replica import FakeGeneratorActor

        delay_s = float(os.environ.get("PTYPE_REPLICA_DELAY_S", "0"))
        return (lambda: FakeGeneratorActor(delay_s=delay_s)), None
    if kind == "paged":
        def make():
            from ptype_tpu.models import transformer as tfm
            from ptype_tpu.serve_engine.engine import PagedGeneratorActor

            serve_class = os.environ.get("PTYPE_REPLICA_SERVE_CLASS",
                                         "unified")
            return PagedGeneratorActor(tfm.preset(preset),
                                       serve_class=serve_class)

        def warmup(actor):
            import jax.numpy as jnp
            import numpy as np

            # One 1-token generate: the decode/prefill programs
            # compile NOW, so activation never pays a cold compile in
            # a scale-up's critical path.
            out = actor.Generate(jnp.ones((1, 4), jnp.int32), 1)
            np.asarray(out)

        return make, warmup
    if kind == "custom":
        # Any actor — a trainer, an eval server, a future engine —
        # rides the same lifecycle: PTYPE_REPLICA_FACTORY names a
        # ``module:function`` whose call returns the actor (an
        # optional ``warmup`` attribute on the function is the
        # warm-up hook). This is how ROADMAP item 5's elastic
        # trainers plug into the reconciler without new worker code.
        spec = os.environ.get("PTYPE_REPLICA_FACTORY", "")
        mod_name, _, fn_name = spec.partition(":")
        if not mod_name or not fn_name:
            raise SystemExit(
                "worker: kind=custom needs "
                "PTYPE_REPLICA_FACTORY=module:function")
        import importlib

        fn = getattr(importlib.import_module(mod_name), fn_name)
        return fn, getattr(fn, "warmup", None)
    raise SystemExit(f"unknown PTYPE_REPLICA_KIND {kind!r} "
                     f"(fake|paged|custom)")


def main() -> None:
    coord_addr = os.environ.get("PTYPE_REPLICA_COORD")
    if not coord_addr:
        raise SystemExit("worker: set PTYPE_REPLICA_COORD=host:port")
    service = os.environ.get("PTYPE_REPLICA_SERVICE", "llm")
    node = os.environ.get("PTYPE_REPLICA_NODE", f"replica-{os.getpid()}")
    kind = os.environ.get("PTYPE_REPLICA_KIND", "paged")
    preset = os.environ.get("PTYPE_REPLICA_PRESET", "tiny")
    warm_hold = os.environ.get("PTYPE_REPLICA_WARM") == "1"
    ready_file = os.environ.get("PTYPE_REPLICA_READY_FILE")
    dom_raw = os.environ.get("PTYPE_REPLICA_DOMAIN", "")
    domain = int(dom_raw) if dom_raw else None

    from ptype_tpu.coord.remote import RemoteCoord
    from ptype_tpu.reconciler.replica import ReplicaHost
    from ptype_tpu.registry import CoordRegistry

    coord = RemoteCoord([coord_addr])
    registry = CoordRegistry(coord)
    factory, warmup = _actor_factory(kind, preset)
    host = ReplicaHost(registry, service, node, factory,
                       warmup=warmup, warm_hold=warm_hold,
                       domain=domain)

    def _term(*_):
        host.request_exit()

    signal.signal(signal.SIGTERM, _term)

    if ready_file:
        tmp = ready_file + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"host": host.host, "port": host.port,
                       "pid": os.getpid()}, f)
        os.replace(tmp, ready_file)  # atomic: spawn never reads half
    log.info("replica worker serving",
             kv={"service": service, "node": node,
                 "addr": host.key, "kind": kind,
                 "warm_hold": warm_hold})
    try:
        host.wait_exit()
    except KeyboardInterrupt:
        pass
    finally:
        host.close()
        coord.close()


if __name__ == "__main__":
    main()
