"""Elastic replica lifecycle (ISSUE 13; ROADMAP item 1).

The control loop that ACTS on the cluster's serving signals: the
gateway's :class:`~ptype_tpu.gateway.slo.ScaleHint` stream and the
health plane's pages drive a reconciler that spawns, drains, and
replaces serving replicas — with hysteresis (cooldown + hint-majority
voting), min/max bounds, warm standbys, and a drain-deadline
escalation path. See docs/OPERATIONS.md "Elastic serving".

- :mod:`~ptype_tpu.reconciler.policy` — the pure decision math;
- :mod:`~ptype_tpu.reconciler.replica` — replica lifecycle's one home
  (host, control endpoints, launchers; lint PT012);
- :mod:`~ptype_tpu.reconciler.worker` — the OS-process replica entry;
- :mod:`~ptype_tpu.reconciler.core` — the reconcile loop.
"""

from ptype_tpu.reconciler.core import (SCALE_UP_RULES, Reconciler,
                                       ReconcilerConfig)
from ptype_tpu.reconciler.policy import (URGENT_REASONS,
                                         HysteresisPolicy,
                                         ScaleDecision)
from ptype_tpu.reconciler.replica import (FakeGeneratorActor,
                                          LocalLauncher,
                                          LocalReplicaHandle,
                                          ProcessLauncher,
                                          ProcessReplicaHandle,
                                          ReplicaCtl, ReplicaHandle,
                                          ReplicaHost, serve_actor)

__all__ = [
    "Reconciler", "ReconcilerConfig", "SCALE_UP_RULES",
    "HysteresisPolicy", "ScaleDecision", "URGENT_REASONS",
    "ReplicaHost", "ReplicaCtl", "ReplicaHandle",
    "LocalReplicaHandle", "ProcessReplicaHandle",
    "LocalLauncher", "ProcessLauncher", "FakeGeneratorActor",
    "serve_actor",
]
