"""Hysteresis policy: turning a noisy hint stream into calm decisions.

The gateway's :class:`~ptype_tpu.gateway.slo.ScaleHint` is computed
per poll from windowed stats, so it FLAPS: a queue hovering at half
depth emits grow/steady/grow/steady, and a fleet at the shrink
threshold alternates shrink hints with steady ones. Acting on every
hint would thrash — spawn, drain, spawn — which is strictly worse
than holding, because every churn costs a prefill-cold replica and a
drain window. This module is the pure decision math between hints and
actions (unit-testable with no cluster, no clock — callers pass
``now``):

- **margin voting** — a decision needs the winning direction to LEAD
  the opposite one by at least ``margin`` votes among the last
  ``window`` observations (with at least ``quorum`` votes seen). A
  flapping stream — alternating or near-balanced — never builds a
  margin whatever its phase, so the count holds steady; a plain
  more-than-half rule fails this, because any odd slice of a strict
  alternation has a one-vote "majority" for whichever sign started
  it;
- **urgency ranking** — a shed-class hint (the gateway is actively
  refusing traffic) outranks any idle-shrink votes in the window and
  bypasses the quorum: capacity that is provably short must not wait
  for consensus while the SLO budget burns;
- **cooldown** — after any transition, further decisions are
  suppressed for ``cooldown_s``: whatever the hint stream does, at
  most ONE transition per cooldown window, which bounds churn even
  when the voting window is fooled;
- **min/max bounds** — the decision is clamped so the fleet can never
  scale below ``min_replicas`` (availability floor) or above
  ``max_replicas`` (budget ceiling).

The policy never actuates: the reconciler owns spawning and draining
(and its drain-deadline escalation); this class owns only "should the
fleet change size, and by how much".
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class ScaleDecision:
    """One policy output: change the fleet by ``delta`` replicas."""

    delta: int
    reason: str
    #: A shed-class hint forced this (skip-the-queue semantics at the
    #: actuation layer too: prefer warm-pool activation over spawn).
    urgent: bool = False
    #: The vote tally that carried the decision (debuggability: the
    #: KVLogger line and the reconcile span both carry it).
    votes: dict = field(default_factory=dict)


#: Hint-reason substrings that mark a vote URGENT: the gateway is
#: actively shedding (or its admission queue is about to force it to).
#: An urgent up-vote outranks every down-vote in the window and skips
#: the quorum — but never the cooldown.
URGENT_REASONS = ("shed",)


class HysteresisPolicy:
    """Majority-vote + cooldown hysteresis over a scale-hint stream.

    ``observe`` is the whole surface: feed it every hint (or
    alert-derived synthetic hint) with the CURRENT replica count and a
    monotonic ``now``; it returns a :class:`ScaleDecision` when the
    window earns one, else None. State is a bounded vote deque plus
    the last-transition stamp — no threads, no clock reads.
    """

    def __init__(self, min_replicas: int = 1, max_replicas: int = 8,
                 cooldown_s: float = 30.0, window: int = 5,
                 quorum: int = 3, margin: int = 2,
                 vote_ttl_s: float | None = None):
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, "
                             f"got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas {max_replicas} < min_replicas "
                f"{min_replicas}")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.cooldown_s = float(cooldown_s)
        self.window = int(window)
        self.quorum = int(quorum)
        self.margin = int(margin)
        #: Votes older than this never count (default: one cooldown —
        #: a stale burst from before a quiet stretch must not combine
        #: with one fresh hint into a phantom margin; a zero cooldown
        #: means no expiry, not instant expiry).
        self.vote_ttl_s = (float(vote_ttl_s) if vote_ttl_s is not None
                           else self.cooldown_s or float("inf"))
        #: (now, sign, |delta|, reason, urgent) — newest last.
        self._votes: list[tuple[float, int, int, str, bool]] = []
        self._last_transition = float("-inf")

    # -------------------------------------------------------------- input

    def observe(self, hint, n_replicas: int,
                now: float) -> ScaleDecision | None:
        """Fold one hint; return a decision when one is earned.

        ``hint`` needs only ``delta`` and ``reason`` attributes (a
        :class:`~ptype_tpu.gateway.slo.ScaleHint`, or anything
        duck-shaped — the reconciler synthesizes votes from health
        alerts the same way). Steady hints (delta == 0) are real
        votes: they dilute a majority, which is exactly how a
        marginal signal fails to act."""
        delta = int(hint.delta)
        reason = str(hint.reason)
        urgent = delta > 0 and any(u in reason for u in URGENT_REASONS)
        sign = (delta > 0) - (delta < 0)
        self._votes.append((now, sign, abs(delta), reason, urgent))
        cut = now - self.vote_ttl_s
        self._votes = [v for v in self._votes
                       if v[0] >= cut][-self.window:]
        return self._decide(int(n_replicas), now)

    def in_cooldown(self, now: float) -> bool:
        return now - self._last_transition < self.cooldown_s

    # ----------------------------------------------------------- decision

    def _decide(self, n_replicas: int,
                now: float) -> ScaleDecision | None:
        if self.in_cooldown(now):
            return None
        votes = list(self._votes)
        up = [v for v in votes if v[1] > 0]
        down = [v for v in votes if v[1] < 0]
        urgent_up = [v for v in up if v[4]]
        tally = {"up": len(up), "down": len(down),
                 "steady": len(votes) - len(up) - len(down),
                 "urgent": len(urgent_up), "window": len(votes)}
        lead = len(up) - len(down)
        direction = 0
        if urgent_up:
            # Shed-burst outranks idle-shrink: capacity is PROVABLY
            # short (requests are being refused) — down-votes in the
            # same window are a stale utilization reading.
            direction, basis = 1, urgent_up[-1]
        elif len(votes) >= self.quorum and lead >= self.margin:
            direction, basis = 1, up[-1]
        elif len(votes) >= self.quorum and -lead >= self.margin:
            direction, basis = -1, down[-1]
        if direction == 0:
            return None
        if direction > 0:
            # Grow by the largest step the winning votes asked for
            # (the gateway sizes its delta to the standing queue).
            magnitude = max(v[2] for v in (urgent_up or up))
        else:
            # Shrink ONE replica at a time whatever the votes say:
            # shrinking is cheap to repeat and expensive to overdo (a
            # too-deep shrink pays a spawn to undo).
            magnitude = 1
        target = max(self.min_replicas,
                     min(self.max_replicas,
                         n_replicas + direction * magnitude))
        delta = target - n_replicas
        if delta == 0:
            return None  # bounds ate the whole step: no transition
        self._last_transition = now
        self._votes.clear()
        return ScaleDecision(delta=delta, reason=basis[3],
                             urgent=bool(urgent_up), votes=tally)
