"""Capacity-frontier measurement: goodput vs offered load, the knee,
and the derived operator curves.

One measured point is a marketing number; a frontier is evidence
(the MLPerf posture, PAPERS.md: arXiv 1909.09756). The sweep replays
**one seeded trace** at each offered rate (``TrafficTrace.at_rate``
compresses the schedule, population untouched) through the open-loop
driver and reads each point's SLO-attributed goodput off the traffic
ledger — a counter the fleet cannot flatter, because sheds, errors,
overruns, and never-issued arrivals all count against it.

The **knee** is the highest offered rate whose goodput fraction still
clears ``min_goodput_pct`` (default 90%): to its left goodput tracks
offered load; to its right the fleet sheds, queues, or blows the TTFT
SLO and goodput decouples. If no point qualifies, the point with the
highest absolute goodput throughput stands in (the sweep started past
saturation — re-sweep lower). ``publish_knee`` stamps the result as
the ``loadgen.knee_rps`` gauge so the health plane's
``capacity-headroom`` rule can warn when *live* offered load runs
sustained above the last *measured* knee — before the SLO burns.

Derived curves:

- :func:`shed_burn_curve` — the shed rate of a run priced against a
  menu of error budgets (burn multiple = shed_rate / budget): how
  long the budget survives at this offered load.
- Scale-up-latency vs burst steepness is a fleet drill, not ledger
  math — ``bench.py --traffic`` runs it with the reconciler wired
  (see docs/OPERATIONS.md "Capacity planning").
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ptype_tpu.loadgen.arrivals import TrafficTrace
from ptype_tpu.loadgen.driver import DriverConfig, OpenLoopDriver
from ptype_tpu.loadgen.ledger import TrafficLedger


@dataclass
class RatePoint:
    """One frontier sample: what was offered, what came back good."""

    offered_rps: float
    achieved_rps: float
    goodput_rps: float
    goodput_pct: float
    ttft_p99_ms: float | None
    e2e_p99_ms: float | None
    shed_pct: float
    overrun_pct: float
    offered: int
    answered: int
    #: Stage-blamed SLO-bad counts + the single worst culprit — WHY
    #: this point's goodput is what it is (forensics attribution).
    slo_bad_stages: dict = field(default_factory=dict)
    culprit_stage: str | None = None

    def as_dict(self) -> dict:
        return {k: (round(v, 3) if isinstance(v, float) else v)
                for k, v in self.__dict__.items()}


@dataclass
class Frontier:
    points: list[RatePoint] = field(default_factory=list)
    knee: RatePoint | None = None

    @property
    def knee_rps(self) -> float | None:
        return self.knee.offered_rps if self.knee else None

    def as_dict(self) -> dict:
        return {"knee_rps": (round(self.knee_rps, 2)
                             if self.knee_rps is not None else None),
                "points": [p.as_dict() for p in self.points]}


def point_from_summary(s: dict) -> RatePoint:
    offered = max(1, s["offered"])
    return RatePoint(
        offered_rps=s["offered_rps"],
        achieved_rps=s["achieved_rps"],
        goodput_rps=s["goodput_rps"],
        goodput_pct=s["goodput_pct"],
        ttft_p99_ms=s["ttft_p99_ms"],
        e2e_p99_ms=s["e2e_p99_ms"],
        shed_pct=100.0 * s["shed"] / offered,
        overrun_pct=100.0 * s["overruns"] / offered,
        offered=s["offered"], answered=s["answered"],
        slo_bad_stages=dict(s.get("slo_bad_stages") or {}),
        culprit_stage=s.get("culprit_stage"))


def locate_knee(points: list[RatePoint],
                min_goodput_pct: float = 90.0) -> RatePoint | None:
    if not points:
        return None
    ok = [p for p in points if p.goodput_pct >= min_goodput_pct]
    if ok:
        return max(ok, key=lambda p: p.offered_rps)
    return max(points, key=lambda p: p.goodput_rps)


def sweep(trace: TrafficTrace, target, rates, *,
          slo_ttft_ms: float | None = None,
          slo_tpot_ms: float | None = None,
          cfg: DriverConfig | None = None,
          min_goodput_pct: float = 90.0,
          settle_s: float = 0.0,
          registry=None,
          on_point=None) -> Frontier:
    """Replay ``trace`` at each rate in ``rates`` (ascending) through
    a fresh open-loop driver + private ledger, and locate the knee.
    ``settle_s`` sleeps between points so the fleet drains its queue
    (a carried-over backlog would charge one rate's sins to the
    next). ``on_point(rate, RatePoint)`` is a progress hook;
    ``registry`` (a node's metrics registry) gets the knee stamped
    via :func:`publish_knee`."""
    import time

    fr = Frontier()
    for i, rate in enumerate(sorted(rates)):
        if i and settle_s > 0:
            time.sleep(settle_s)  # ptlint: disable=PT002 -- a fixed inter-point drain pause, not a poll: the fleet must empty its queue so one rate's backlog cannot charge the next point
        led = TrafficLedger(slo_ttft_ms=slo_ttft_ms,
                            slo_tpot_ms=slo_tpot_ms,
                            offered_rps=rate)
        OpenLoopDriver(trace.at_rate(rate), target, ledger=led,
                       cfg=cfg).run()
        p = point_from_summary(led.summary())
        p.offered_rps = float(rate)  # the sweep's set rate, not the
        fr.points.append(p)          # trace's empirical estimate
        if on_point is not None:
            on_point(rate, p)
    fr.knee = locate_knee(fr.points, min_goodput_pct)
    if registry is not None and fr.knee_rps is not None:
        publish_knee(registry, fr.knee_rps)
    return fr


def publish_knee(registry, knee_rps: float) -> None:
    """Stamp the last-measured knee where the sampler (and so the
    ``capacity-headroom`` rule and ``obs traffic``) can see it."""
    registry.gauge("loadgen.knee_rps").set(float(knee_rps))


def shed_burn_curve(summary: dict,
                    budgets=(0.001, 0.01, 0.05, 0.1)) -> list[dict]:
    """Price one run's shed rate against a menu of error budgets.
    ``burn`` is the classic multiple (1.0 = spending the budget
    exactly on schedule; 14.4 = the fast-burn page threshold) — the
    same math the gateway's :meth:`SLOTracker.burn_rate` and the
    ``slo-burn-rate`` health rule use, so all three agree."""
    offered = max(1, summary["offered"])
    shed_rate = summary["shed"] / offered
    return [{"budget": b, "shed_rate": round(shed_rate, 4),
             "burn": round(shed_rate / b, 2)} for b in budgets]
