"""Open-loop traffic observatory (ISSUE 19).

Trace-driven load generation and capacity measurement for the serving
stack: seeded arrival processes + shared-prefix request populations
(:mod:`~ptype_tpu.loadgen.arrivals`), an open-loop driver that issues
on schedule whether or not the fleet keeps up
(:mod:`~ptype_tpu.loadgen.driver`), a :class:`TrafficLedger`
publishing ``loadgen.*`` series through the sampler/telemetry plane
(:mod:`~ptype_tpu.loadgen.ledger`), and the capacity-frontier sweep
that turns rate points into a measured knee
(:mod:`~ptype_tpu.loadgen.frontier`). One seeded RNG home
(:mod:`~ptype_tpu.loadgen.rng`, ptlint PT024) keeps every trace
replayable from its seed. See docs/OBSERVABILITY.md "Traffic plane"
and docs/OPERATIONS.md "Capacity planning".
"""

from ptype_tpu.loadgen.arrivals import (AGENT, CHAT, DEFAULT_MIX, RAG,
                                        Arrival, FamilySpec,
                                        TrafficTrace, bursty_schedule,
                                        diurnal_schedule,
                                        poisson_schedule,
                                        prompt_tokens, synth_trace)
from ptype_tpu.loadgen.driver import (ClosedLoopDriver, DriverConfig,
                                      OpenLoopDriver, gateway_target)
from ptype_tpu.loadgen.frontier import (Frontier, RatePoint,
                                        locate_knee, publish_knee,
                                        point_from_summary,
                                        shed_burn_curve, sweep)
from ptype_tpu.loadgen.ledger import Outcome, TrafficLedger
from ptype_tpu.loadgen.rng import TraceRng

__all__ = [
    "Arrival", "FamilySpec", "TrafficTrace", "synth_trace",
    "prompt_tokens", "poisson_schedule", "bursty_schedule",
    "diurnal_schedule", "CHAT", "RAG", "AGENT", "DEFAULT_MIX",
    "OpenLoopDriver", "ClosedLoopDriver", "DriverConfig",
    "gateway_target",
    "TrafficLedger", "Outcome",
    "Frontier", "RatePoint", "sweep", "locate_knee", "publish_knee",
    "point_from_summary", "shed_burn_curve",
    "TraceRng",
]
