"""The ONE seeded randomness home for the load-generation plane.

Every draw in ``ptype_tpu.loadgen`` — arrival gaps, family picks,
prompt/output lengths, prefix token content — flows through a
:class:`TraceRng`, and ptlint PT024 fails the build on any raw
``random.*`` / ``np.random.*`` call elsewhere in the package. The
point is replay: a traffic trace is evidence (the capacity frontier,
the spike drill, a chaos-soak composition all cite one), and evidence
must be reproducible from ``(seed,)`` alone, the same determinism
discipline the chaos plan rides (:mod:`ptype_tpu.chaos`).

Streams are *forked by tag*, not shared: the schedule and the request
population draw from independent children of the root seed
(``fork("schedule")`` / ``fork("population")``), so changing how many
timestamps a process draws cannot shift which prompts the population
samples — two traces with the same seed and different rates still
carry the same request mix. Child seeds derive through SHA-256, which
is stable across Python builds (``hash()`` is salted per process and
would silently break replay).
"""

from __future__ import annotations

import hashlib
import math
import random


def _derive(seed, salt: str) -> int:
    h = hashlib.sha256(f"{seed}\x00{salt}".encode()).digest()
    return int.from_bytes(h[:8], "big")


class TraceRng:
    """A seeded, forkable draw stream (stdlib Mersenne under the hood;
    the distribution helpers the traffic models need, nothing more)."""

    def __init__(self, seed, salt: str = ""):
        self.seed = seed
        self.salt = salt
        self._r = random.Random(_derive(seed, salt))

    def fork(self, tag) -> "TraceRng":
        """An independent child stream: deterministic in ``(seed,
        salt, tag)``, unaffected by how much this stream has drawn."""
        return TraceRng(self.seed, f"{self.salt}/{tag}")

    # ------------------------------------------------------- raw draws

    def random(self) -> float:
        return self._r.random()

    def uniform(self, a: float, b: float) -> float:
        return self._r.uniform(a, b)

    def randint(self, a: int, b: int) -> int:
        return self._r.randint(a, b)

    def expovariate(self, rate: float) -> float:
        return self._r.expovariate(rate)

    def lognormal(self, mu: float, sigma: float) -> float:
        return self._r.lognormvariate(mu, sigma)

    # ------------------------------------------------ shaped helpers

    def heavy_len(self, mu: float, sigma: float, lo: int,
                  hi: int) -> int:
        """A heavy-tailed integer length: lognormal body clamped to
        ``[lo, hi]`` — the prompt/output-length shape serving traces
        show (most requests short, a fat tail of huge ones)."""
        return max(lo, min(hi, int(round(self.lognormal(mu, sigma)))))

    def pick_weighted(self, pairs):
        """One item from ``[(item, weight), ...]``."""
        total = math.fsum(w for _, w in pairs)
        x = self._r.random() * total
        acc = 0.0
        for item, w in pairs:
            acc += w
            if x < acc:
                return item
        return pairs[-1][0]

    def token_row(self, n: int, vocab: int) -> list[int]:
        """``n`` token ids in ``[1, vocab)`` (0 is reserved for pad)."""
        return [self._r.randrange(1, vocab) for _ in range(n)]
