"""Open-loop (and reference closed-loop) traffic drivers.

The open-loop driver is the whole point of the plane: it issues
requests **on the trace's schedule, whether or not prior requests
completed**. A closed-loop driver (N workers, each waiting for its
response before sending the next) self-throttles exactly in the
overload regime the SLO rules, the reconciler, and the disagg router
exist for — offered load silently sags to match capacity and the
measured tail flatters the fleet. The MLPerf server scenario
(PAPERS.md: arXiv 1909.09756) is open-loop for the same reason.

Never-closed-loop contract, mechanically enforced:

- The issue loop only ever *sleeps until the next scheduled arrival*;
  it never waits on a completion.
- In-flight requests live in a **bounded ledger** (``max_inflight``).
  When the bound is hit, the arrival is refused and recorded as an
  ``overrun`` outcome — refusing is honest (the fleet was offered a
  request it never saw, and goodput accounts it), waiting is not.
- When the loop itself falls behind schedule by more than
  ``overrun_tolerance_s`` (driver starvation, a chaos delay), the
  issue still happens but ``loadgen.overrun`` counts it and
  ``loadgen.issue_lag_ms`` records the slip — a loaded driver can
  never silently degrade into a closed-loop one; the evidence is in
  the series.

Chaos seam (site table: :mod:`ptype_tpu.chaos`): each arrival passes
``chaos.hit("loadgen.issue", key=<seq>)`` before issue — ``drop``
swallows the arrival (recorded as ``dropped``), ``delay`` stalls the
issue (surfacing as overrun/lag, exactly like a wedged driver host).
Every answered request reports ``chaos.note_ok`` so drills can assert
paired recovery, and traffic replay composes with the chaos soak.

Targets are callables ``target(arrival) -> result``: a raw token
array (tokens counted from its shape), or a dict with optional
``tokens`` / ``ttft_ms`` / ``tpot_ms`` keys when the target can
report first-token timing, plus ``stages`` / ``trace_id`` when it can
report the gateway's per-stage wall split (the ledger prices those
against the TTFT stage budgets to blame each SLO-bad request on a
culprit stage). :func:`gateway_target` adapts an
:class:`~ptype_tpu.gateway.InferenceGateway` and reports all five.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from ptype_tpu import chaos, lockcheck
from ptype_tpu.errors import ShedError
from ptype_tpu.loadgen.arrivals import TrafficTrace, prompt_tokens
from ptype_tpu.loadgen.ledger import Outcome, TrafficLedger

SITE = "loadgen.issue"


@dataclass
class DriverConfig:
    max_inflight: int = 512          #: bounded in-flight ledger
    overrun_tolerance_s: float = 0.02
    deadline_s: float = 10.0         #: per-request gateway deadline
    join_timeout_s: float = 60.0     #: post-trace drain budget


def _parse_result(res) -> tuple[int, float | None, float | None]:
    """(tokens, ttft_ms, tpot_ms) from a target's return value."""
    if isinstance(res, dict):
        return (int(res.get("tokens", 0) or 0),
                res.get("ttft_ms"), res.get("tpot_ms"))
    shape = getattr(res, "shape", None)
    if shape:
        n = 1
        for d in shape:
            n *= int(d)
        return n, None, None
    return 0, None, None


class OpenLoopDriver:
    """Replay a :class:`TrafficTrace` against a target, open-loop."""

    def __init__(self, trace: TrafficTrace, target, *,
                 ledger: TrafficLedger | None = None,
                 cfg: DriverConfig | None = None):
        self.trace = trace
        self.target = target
        self.cfg = cfg or DriverConfig()
        self.ledger = ledger if ledger is not None else TrafficLedger(
            offered_rps=trace.offered_rps())
        self._lock = lockcheck.lock("loadgen.driver")

    def run(self) -> TrafficLedger:
        cfg, led = self.cfg, self.ledger
        t0 = time.monotonic()
        threads: list[threading.Thread] = []
        for arr in self.trace.arrivals:
            sched = t0 + arr.t
            delay = sched - time.monotonic()
            if delay > 0:
                time.sleep(delay)  # ptlint: disable=PT002 -- the open-loop pacer: sleeping to the next scheduled arrival IS the algorithm, not a retry poll
            led.offered()
            key = f"{arr.seq:06d}"
            f = chaos.hit(SITE, key)
            if f is not None:
                if f.action == "drop":
                    led.record(Outcome(arr.seq, arr.family, "dropped",
                                       t_offered=arr.t))
                    continue
                f.sleep()  # "delay": a wedged driver host
            lag = time.monotonic() - sched
            if lag > cfg.overrun_tolerance_s:
                led.overrun(lag_ms=lag * 1000.0)
            if led.inflight(0) >= cfg.max_inflight:
                # Bound hit: refuse, record, move on. NEVER wait — a
                # waiting open-loop driver is a closed-loop driver.
                led.record(Outcome(arr.seq, arr.family, "overrun",
                                   t_offered=arr.t))
                continue
            led.issued(lag * 1000.0)
            th = threading.Thread(target=self._fire,
                                  args=(arr, t0, key), daemon=True)
            th.start()
            threads.append(th)
        deadline = time.monotonic() + cfg.join_timeout_s
        for th in threads:
            th.join(timeout=max(0.0, deadline - time.monotonic()))
        led.seal(time.monotonic() - t0)
        return led

    def _fire(self, arr, t0: float, key: str) -> None:
        led = self.ledger
        led.inflight(+1)
        issued = time.monotonic() - t0
        try:
            try:
                res = self.target(arr)
            except ShedError:
                led.record(Outcome(arr.seq, arr.family, "shed",
                                   t_offered=arr.t, t_issued=issued,
                                   t_done=time.monotonic() - t0))
                return
            except Exception:
                led.record(Outcome(arr.seq, arr.family, "error",
                                   t_offered=arr.t, t_issued=issued,
                                   t_done=time.monotonic() - t0))
                return
            done = time.monotonic() - t0
            chaos.note_ok(SITE, key)
            tokens, ttft_ms, tpot_ms = _parse_result(res)
            if (tpot_ms is None and ttft_ms is not None
                    and tokens > 1):
                tpot_ms = max(0.0, ((done - issued) * 1000.0
                                    - ttft_ms)) / (tokens - 1)
            stages = trace_id = None
            if isinstance(res, dict):
                stages = res.get("stages")
                trace_id = res.get("trace_id")
            led.record(Outcome(arr.seq, arr.family, "ok",
                               t_offered=arr.t, t_issued=issued,
                               t_done=done, tokens=tokens,
                               ttft_ms=ttft_ms, tpot_ms=tpot_ms,
                               stages=stages, trace_id=trace_id))
        finally:
            led.inflight(-1)


class ClosedLoopDriver:
    """The self-throttling reference: ``concurrency`` workers, each
    waiting for its response before taking the next arrival. Exists
    so the open-vs-closed blind spot is *demonstrated* on the same
    fleet (tests, docs) — never use this to measure capacity."""

    def __init__(self, trace: TrafficTrace, target, *,
                 concurrency: int = 4,
                 ledger: TrafficLedger | None = None):
        self.trace = trace
        self.target = target
        self.concurrency = int(concurrency)
        self.ledger = ledger if ledger is not None else TrafficLedger()
        self._lock = lockcheck.lock("loadgen.closed_driver")
        self._next = 0

    def run(self) -> TrafficLedger:
        t0 = time.monotonic()
        arrivals = self.trace.arrivals

        def worker():
            while True:
                with self._lock:
                    i = self._next
                    self._next += 1
                if i >= len(arrivals):
                    return
                arr = arrivals[i]
                self.ledger.offered()
                self.ledger.issued(0.0)
                issued = time.monotonic() - t0
                try:
                    res = self.target(arr)
                except ShedError:
                    self.ledger.record(Outcome(
                        arr.seq, arr.family, "shed", t_offered=issued,
                        t_issued=issued,
                        t_done=time.monotonic() - t0))
                    continue
                except Exception:
                    self.ledger.record(Outcome(
                        arr.seq, arr.family, "error",
                        t_offered=issued, t_issued=issued,
                        t_done=time.monotonic() - t0))
                    continue
                tokens, ttft_ms, tpot_ms = _parse_result(res)
                self.ledger.record(Outcome(
                    arr.seq, arr.family, "ok", t_offered=issued,
                    t_issued=issued, t_done=time.monotonic() - t0,
                    tokens=tokens, ttft_ms=ttft_ms, tpot_ms=tpot_ms))

        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.concurrency)]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        self.ledger.seal(time.monotonic() - t0)
        return self.ledger


def gateway_target(gw, *, deadline_s: float | None = None,
                   vocab: int = 32000):
    """Adapt an :class:`~ptype_tpu.gateway.InferenceGateway` into a
    driver target: real prompt tokens (shared prefixes intact),
    affinity-keyed routing, typed sheds propagated."""

    def target(arr):
        prompt = prompt_tokens(arr, vocab=vocab)
        out = gw.generate(prompt, arr.max_new,
                          deadline_s=deadline_s,
                          affinity_key=arr.affinity_key)
        tokens, _, _ = _parse_result(out)
        rep = {"tokens": tokens}
        # The SLO tracker stamps its thread-local with the request the
        # calling thread just finished — gw.generate ran right here,
        # so this is OUR request's stage split and trace id, with no
        # tracing dependency and no extra RPC.
        slo = getattr(gw, "slo", None)
        last = slo.last_request() if slo is not None else None
        if last is not None:
            rep["stages"] = last.get("stages")
            rep["trace_id"] = last.get("trace_id")
            if last.get("ttft_ms") is not None:
                rep["ttft_ms"] = last["ttft_ms"]
            if last.get("tpot_ms") is not None:
                rep["tpot_ms"] = last["tpot_ms"]
        return rep

    return target
