"""The TrafficLedger: per-request outcomes + the ``loadgen.*`` series.

Where the serving ledger (:mod:`ptype_tpu.health.serving`) records
what the *fleet* did, the traffic ledger records what was *asked of
it* and what came back — from the open-loop driver's vantage point,
which is the only vantage that sees offered-vs-achieved: a request
that was scheduled but never answered (shed, errored, dropped by a
chaos fault, or refused at the in-flight bound) still exists here.

Outcome statuses:

==========  =========================================================
``ok``      answered; TTFT/TPOT/e2e recorded and SLO-attributed
``shed``    typed :class:`~ptype_tpu.errors.ShedError` from the stack
``error``   any other failure out of the target
``dropped`` a ``loadgen.issue`` chaos fault swallowed the arrival
``overrun`` the bounded in-flight ledger was full at issue time —
            the driver refused to issue rather than wait (waiting is
            how an open-loop harness silently becomes closed-loop)
==========  =========================================================

Metric names (flat, one traffic plane per registry — pass a private
registry per sweep point so counters never bleed across points, or
the node's registry so the sampler publishes the series):

==============================  ======================================
``loadgen.offered``             arrivals that reached issue time (ctr)
``loadgen.issued``              actually handed to the target (ctr)
``loadgen.answered``            ``ok`` outcomes (ctr)
``loadgen.shed``                typed sheds (ctr)
``loadgen.errors``              non-shed failures (ctr)
``loadgen.dropped``             chaos-dropped arrivals (ctr)
``loadgen.overrun``             late or bound-refused issues (ctr)
``loadgen.slo_good``            answered AND met TTFT+TPOT SLOs (ctr)
``loadgen.slo_bad``             everything else offered (ctr)
``loadgen.slo_bad.<culprit>``   slo_bad attributed to its culprit
                                stage (gateway stage timings priced
                                against the TTFT stage budgets); a
                                shed blames ``queue-wait``, other
                                non-answers blame their status (ctr)
``loadgen.inflight``            open requests at the driver (gauge)
``loadgen.offered_rps``         the schedule's target rate (gauge)
``loadgen.knee_rps``            last measured capacity knee (gauge,
                                stamped by the frontier sweep)
``loadgen.ttft_ms``             per-request TTFT (histogram)
``loadgen.tpot_ms``             per-request TPOT (histogram)
``loadgen.e2e_ms``              per-request e2e (histogram)
``loadgen.issue_lag_ms``        scheduled-vs-actual issue lag (hist)
==============================  ======================================

SLO attribution: a request is **good** only if it was answered and
met both the TTFT and TPOT SLOs. When the target cannot report a
per-request TTFT (a non-streaming path), the e2e latency stands in as
a conservative upper bound — TTFT ≤ e2e always, so the substitution
can only *under*-count goodput, never inflate it. A TPOT SLO with no
TPOT sample (single-token request) counts as met.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ptype_tpu import lockcheck
from ptype_tpu import metrics as metrics_mod


@dataclass
class Outcome:
    """One request's fate, stamped from the driver's clock (seconds
    from driver start, so offered-vs-achieved is directly readable)."""

    seq: int
    family: str
    status: str                  # ok|shed|error|dropped|overrun
    t_offered: float             # scheduled issue offset
    t_issued: float | None = None
    t_done: float | None = None
    tokens: int = 0
    ttft_ms: float | None = None
    tpot_ms: float | None = None
    #: Gateway stage decomposition of this request's wall (name → ms),
    #: read off the SLO tracker's thread-local by the driver target —
    #: no tracing dependency, works on every answered request.
    stages: dict | None = None
    #: The request's trace id when tracing was armed — links an SLO-bad
    #: outcome to its replayable waterfall (``obs request``).
    trace_id: str | None = None

    @property
    def e2e_ms(self) -> float | None:
        if self.t_issued is None or self.t_done is None:
            return None
        return (self.t_done - self.t_issued) * 1000.0


class TrafficLedger:
    """Outcome sink + ``loadgen.*`` publisher for one traffic run."""

    def __init__(self, *, slo_ttft_ms: float | None = None,
                 slo_tpot_ms: float | None = None,
                 registry: metrics_mod.MetricsRegistry | None = None,
                 offered_rps: float | None = None):
        # Default to a PRIVATE registry: a frontier sweep builds one
        # ledger per rate point, and cumulative counters must not
        # bleed between points. Pass the node's registry to publish.
        self._reg = (registry if registry is not None
                     else metrics_mod.MetricsRegistry())
        self.slo_ttft_ms = slo_ttft_ms
        self.slo_tpot_ms = slo_tpot_ms
        reg = self._reg
        self.c_offered = reg.counter("loadgen.offered")
        self.c_issued = reg.counter("loadgen.issued")
        self.c_answered = reg.counter("loadgen.answered")
        self.c_shed = reg.counter("loadgen.shed")
        self.c_errors = reg.counter("loadgen.errors")
        self.c_dropped = reg.counter("loadgen.dropped")
        self.c_overrun = reg.counter("loadgen.overrun")
        self.c_good = reg.counter("loadgen.slo_good")
        self.c_bad = reg.counter("loadgen.slo_bad")
        self.g_inflight = reg.gauge("loadgen.inflight")
        self.g_offered_rps = reg.gauge("loadgen.offered_rps")
        if offered_rps is not None:
            self.g_offered_rps.set(float(offered_rps))
        self.h_ttft = reg.histogram("loadgen.ttft_ms")
        self.h_tpot = reg.histogram("loadgen.tpot_ms")
        self.h_e2e = reg.histogram("loadgen.e2e_ms")
        self.h_lag = reg.histogram("loadgen.issue_lag_ms")
        self._lock = lockcheck.lock("loadgen.ledger")
        self._outcomes: list[Outcome] = []
        self._inflight = 0
        self._wall_s: float | None = None
        # Stage budgets for culprit attribution: the TTFT SLO
        # decomposed per stage (lazy import keeps loadgen light for
        # targets that never price stages).
        self._budgets: dict | None = None
        if slo_ttft_ms is not None:
            from ptype_tpu.health import forensics
            self._budgets = forensics.stage_budgets_ms(slo_ttft_ms)
        self._culprits: dict[str, int] = {}

    @property
    def registry(self) -> metrics_mod.MetricsRegistry:
        return self._reg

    # ------------------------------------------------------- intake

    def offered(self) -> None:
        self.c_offered.add(1)

    def overrun(self, lag_ms: float | None = None) -> None:
        self.c_overrun.add(1)
        if lag_ms is not None:
            self.h_lag.observe(lag_ms)

    def inflight(self, delta: int) -> int:
        with self._lock:
            self._inflight += delta
            n = self._inflight
        self.g_inflight.set(n)
        return n

    def issued(self, lag_ms: float) -> None:
        self.c_issued.add(1)
        self.h_lag.observe(max(0.0, lag_ms))

    def good(self, out: Outcome) -> bool:
        """SLO attribution (see module docstring for the fallback)."""
        if out.status != "ok":
            return False
        if self.slo_ttft_ms is not None:
            ttft = out.ttft_ms if out.ttft_ms is not None else out.e2e_ms
            if ttft is None or ttft > self.slo_ttft_ms:
                return False
        if (self.slo_tpot_ms is not None and out.tpot_ms is not None
                and out.tpot_ms > self.slo_tpot_ms):
            return False
        return True

    def culprit_of(self, out: Outcome) -> str | None:
        """The stage (or status) to blame for an SLO-bad outcome: the
        gateway's per-request stage split priced against the TTFT
        stage budgets when the target reported one; a shed blames
        ``queue-wait`` (the admission gate IS queue pressure); other
        non-answers blame their status so nothing vanishes."""
        if self.good(out):
            return None
        if out.stages:
            from ptype_tpu.health import forensics
            return forensics.culprit_stage(out.stages, self._budgets)
        if out.status == "shed":
            return "queue-wait"
        return out.status if out.status != "ok" else "unattributed"

    def record(self, out: Outcome) -> None:
        if out.status == "ok":
            self.c_answered.add(1)
            e2e = out.e2e_ms
            if e2e is not None:
                self.h_e2e.observe(e2e, out.trace_id)
                ttft = (out.ttft_ms if out.ttft_ms is not None
                        else e2e)
                self.h_ttft.observe(ttft, out.trace_id)
            if out.tpot_ms is not None:
                self.h_tpot.observe(out.tpot_ms, out.trace_id)
        elif out.status == "shed":
            self.c_shed.add(1)
        elif out.status == "error":
            self.c_errors.add(1)
        elif out.status == "dropped":
            self.c_dropped.add(1)
        elif out.status == "overrun":
            self.c_overrun.add(1)
        if self.good(out):
            self.c_good.add(1)
        else:
            self.c_bad.add(1)
            culprit = self.culprit_of(out)
            if culprit:
                self._reg.counter(f"loadgen.slo_bad.{culprit}").add(1)
                with self._lock:
                    self._culprits[culprit] = (
                        self._culprits.get(culprit, 0) + 1)
        with self._lock:
            self._outcomes.append(out)

    def seal(self, wall_s: float) -> None:
        """Stamp the run's wall clock (achieved-rate denominator)."""
        with self._lock:
            self._wall_s = float(wall_s)

    # ----------------------------------------------------- readouts

    def outcomes(self) -> list[Outcome]:
        with self._lock:
            return list(self._outcomes)

    def _pct(self, vals: list[float], p: float) -> float | None:
        if not vals:
            return None
        vals = sorted(vals)
        i = min(len(vals) - 1, int(round((p / 100.0) * (len(vals) - 1))))
        return vals[i]

    def summary(self) -> dict:
        """The run distilled: counts, tails, offered vs achieved, and
        SLO-attributed goodput (good / offered — sheds, errors, chaos
        drops, and overruns all count against it: they were asked)."""
        outs = self.outcomes()
        with self._lock:
            wall = self._wall_s
            culprits = dict(self._culprits)
        by = lambda s: [o for o in outs if o.status == s]  # noqa: E731
        ok = by("ok")
        ttfts = [(o.ttft_ms if o.ttft_ms is not None else o.e2e_ms)
                 for o in ok]
        ttfts = [t for t in ttfts if t is not None]
        e2es = [o.e2e_ms for o in ok if o.e2e_ms is not None]
        good = sum(1 for o in outs if self.good(o))
        offered = len(outs)
        if wall is None and outs:
            wall = max((o.t_done or o.t_offered) for o in outs)
        wall = wall or 0.0
        return {
            "offered": offered,
            "answered": len(ok),
            "shed": len(by("shed")),
            "errors": len(by("error")),
            "dropped": len(by("dropped")),
            "overruns": int(self.c_overrun.value),
            "good": good,
            "goodput_pct": (100.0 * good / offered if offered else 0.0),
            "offered_rps": (offered / wall if wall > 0 else 0.0),
            "achieved_rps": (len(ok) / wall if wall > 0 else 0.0),
            "goodput_rps": (good / wall if wall > 0 else 0.0),
            "ttft_p50_ms": self._pct(ttfts, 50),
            "ttft_p99_ms": self._pct(ttfts, 99),
            "e2e_p99_ms": self._pct(e2es, 99),
            "wall_s": wall,
            # WHY the knee is where it is: every slo_bad request blamed
            # on its culprit stage, plus the single worst stage — what
            # bench --traffic reports next to the knee.
            "slo_bad_stages": culprits,
            "culprit_stage": (max(culprits, key=culprits.get)
                              if culprits else None),
        }


def now_s(t0: float) -> float:
    """Driver-relative clock stamp."""
    return time.monotonic() - t0
