"""Arrival processes and request populations — the trace half of the
load plane.

A :class:`TrafficTrace` is the unit of replay: a seeded, materialized
schedule of :class:`Arrival` records (when each request fires, and
exactly what it is). Same seed → identical timestamps AND identical
request population, so a capacity number, a spike drill, or a chaos
soak composed over a trace can be re-run bit-for-bit from the seed in
its report (the same contract the chaos plan's ``FaultPlan`` keeps).

Three arrival processes cover the regimes the serving stack must be
measured in:

- ``poisson`` — memoryless steady load; the frontier sweep's default
  (offered rate is the one knob, which is what a rate sweep wants).
- ``bursty`` — a two-state Markov-modulated Poisson process (on/off):
  exponential dwell in a quiet state and a burst state, Poisson within
  each. Exercises admission, shedding, and the reconciler's
  spike-to-capacity lag at controllable steepness.
- ``diurnal`` — an inhomogeneous Poisson replay of a compressed
  daily cycle: a sinusoidal rate envelope raised to a sharpness power
  so the peak narrows into a rush-hour spike, sampled exactly by
  thinning. The spike drill replays one of these against a static and
  an elastic fleet and compares TTFT tails from the *same* trace.

Request populations mix three shared-prefix families (chat / RAG /
agentic tool-loop) with heavy-tailed lognormal prompt/output lengths,
so prefix-affinity routing, disagg prefill/decode splits, and KV
pressure are all exercised by synthetic traffic the way production
traffic exercises them. Prefix token *content* is deterministic in
``(family, prefix_id)`` — two arrivals in the same prefix group carry
an identical real token prefix, not just an affinity label.

Every draw goes through the package's seeded RNG home
(:mod:`ptype_tpu.loadgen.rng`; enforced by ptlint PT024).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from ptype_tpu.loadgen.rng import TraceRng


@dataclass(frozen=True)
class Arrival:
    """One scheduled request: its firing offset and its identity."""

    seq: int
    t: float          #: schedule offset (s) from trace start
    family: str       #: "chat" | "rag" | "agent"
    prefix_id: int    #: shared-prefix group within the family
    prompt_len: int   #: total prompt tokens (shared prefix + suffix)
    prefix_len: int   #: tokens shared verbatim across the group
    max_new: int      #: decode budget

    @property
    def affinity_key(self) -> str:
        """The gateway routing key: one per shared-prefix group, so
        affinity routing lands the group on one replica's KV cache."""
        return f"{self.family}:{self.prefix_id:04d}"


@dataclass(frozen=True)
class FamilySpec:
    """One population family's shape knobs (lognormal ``mu``/``sigma``
    are of the token counts; the clamp bounds keep the tail heavy but
    finite)."""

    name: str
    weight: float
    prefix_pool: int     #: distinct shared prefixes in the family
    prefix_len: int      #: tokens shared verbatim per group
    prompt_mu: float     #: lognormal body of the unique suffix length
    prompt_sigma: float
    prompt_max: int
    out_mu: float        #: lognormal body of the decode budget
    out_sigma: float
    out_max: int


#: Chat: mid prompts, mid outputs, a handful of system prompts shared
#: very widely — the prefix-cache bread and butter.
CHAT = FamilySpec("chat", weight=0.5, prefix_pool=4, prefix_len=32,
                  prompt_mu=3.2, prompt_sigma=0.9, prompt_max=512,
                  out_mu=3.2, out_sigma=0.7, out_max=256)
#: RAG: long stuffed-context prompts (the heavy tail lives here),
#: short grounded answers, more distinct prefixes (one per corpus).
RAG = FamilySpec("rag", weight=0.3, prefix_pool=8, prefix_len=96,
                 prompt_mu=4.8, prompt_sigma=1.1, prompt_max=2048,
                 out_mu=2.6, out_sigma=0.6, out_max=128)
#: Agentic tool loop: few prefixes (the agent scaffold), many short
#: turns against the same prefix — KV-reuse and TPOT pressure.
AGENT = FamilySpec("agent", weight=0.2, prefix_pool=2, prefix_len=64,
                   prompt_mu=2.8, prompt_sigma=0.6, prompt_max=256,
                   out_mu=2.2, out_sigma=0.5, out_max=64)

DEFAULT_MIX: tuple[FamilySpec, ...] = (CHAT, RAG, AGENT)


# ------------------------------------------------------------ schedules


def poisson_schedule(rng: TraceRng, rate_rps: float,
                     duration_s: float) -> list[float]:
    """Homogeneous Poisson arrivals over ``[0, duration_s)``."""
    out, t = [], 0.0
    if rate_rps <= 0:
        return out
    while True:
        t += rng.expovariate(rate_rps)
        if t >= duration_s:
            return out
        out.append(t)


def bursty_schedule(rng: TraceRng, duration_s: float, *,
                    base_rps: float, burst_rps: float,
                    mean_on_s: float = 0.5,
                    mean_off_s: float = 1.0) -> list[float]:
    """Markov-modulated on/off Poisson: exponential dwell times in a
    quiet (``base_rps``) and a burst (``burst_rps``) state."""
    out: list[float] = []
    t, on = 0.0, False
    while t < duration_s:
        dwell = rng.expovariate(
            1.0 / (mean_on_s if on else mean_off_s))
        end = min(duration_s, t + dwell)
        rate = burst_rps if on else base_rps
        if rate > 0:
            tick = t
            while True:
                tick += rng.expovariate(rate)
                if tick >= end:
                    break
                out.append(tick)
        t, on = end, not on
    return out


def diurnal_schedule(rng: TraceRng, duration_s: float, *,
                     trough_rps: float, peak_rps: float,
                     period_s: float | None = None,
                     sharpness: float = 4.0) -> list[float]:
    """Inhomogeneous Poisson replay of a compressed daily cycle,
    sampled exactly by thinning: rate(t) = trough + (peak - trough) ·
    (½ − ½cos(2πt/period))^sharpness. Sharpness narrows the peak into
    a rush-hour spike (at period/2) without moving the trough."""
    period = duration_s if period_s is None else period_s

    def rate(t: float) -> float:
        env = (0.5 - 0.5 * math.cos(2 * math.pi * t / period))
        return trough_rps + (peak_rps - trough_rps) * env ** sharpness

    out, t = [], 0.0
    if peak_rps <= 0:
        return out
    while True:
        t += rng.expovariate(peak_rps)
        if t >= duration_s:
            return out
        if rng.random() * peak_rps < rate(t):
            out.append(t)


_SCHEDULES = {"poisson", "bursty", "diurnal"}


# ---------------------------------------------------------- population


def _sample_request(rng: TraceRng,
                    mix: tuple[FamilySpec, ...]) -> tuple:
    fam = rng.pick_weighted([(f, f.weight) for f in mix])
    prefix_id = rng.randint(0, fam.prefix_pool - 1)
    suffix = rng.heavy_len(fam.prompt_mu, fam.prompt_sigma, 1,
                           fam.prompt_max)
    max_new = rng.heavy_len(fam.out_mu, fam.out_sigma, 1, fam.out_max)
    return (fam.name, prefix_id, fam.prefix_len + suffix,
            fam.prefix_len, max_new)


@dataclass(frozen=True)
class TrafficTrace:
    """A seeded, fully materialized arrival schedule + population."""

    seed: object
    process: str
    duration_s: float
    arrivals: tuple[Arrival, ...]

    def offered_rps(self) -> float:
        return (len(self.arrivals) / self.duration_s
                if self.duration_s > 0 else 0.0)

    def at_rate(self, rate_rps: float) -> "TrafficTrace":
        """The SAME trace replayed at a different offered rate: the
        schedule is affinely compressed/stretched; the population —
        every prompt, prefix group, and decode budget, in order — is
        untouched. This is what lets one seeded trace back every
        point of a capacity frontier ('the same traffic, faster')."""
        cur = self.offered_rps()
        if cur <= 0 or rate_rps <= 0:
            return self
        k = cur / rate_rps
        arrivals = tuple(
            Arrival(a.seq, a.t * k, a.family, a.prefix_id,
                    a.prompt_len, a.prefix_len, a.max_new)
            for a in self.arrivals)
        return TrafficTrace(self.seed, self.process,
                            self.duration_s * k, arrivals)


def synth_trace(seed, *, process: str = "poisson",
                duration_s: float = 10.0,
                mix: tuple[FamilySpec, ...] = DEFAULT_MIX,
                **kw) -> TrafficTrace:
    """Build a trace. ``kw`` are the process's rate knobs
    (``rate_rps`` for poisson; ``base_rps``/``burst_rps``/dwell means
    for bursty; ``trough_rps``/``peak_rps``/``sharpness`` for
    diurnal). Schedule and population draw from independent forks of
    the seed, so the same seed at a different rate still samples the
    same request mix per arrival index."""
    if process not in _SCHEDULES:
        raise ValueError(f"unknown arrival process {process!r}; "
                         f"pick one of {sorted(_SCHEDULES)}")
    root = TraceRng(seed, salt="loadgen")
    sched_rng = root.fork("schedule")
    if process == "poisson":
        times = poisson_schedule(sched_rng, duration_s=duration_s,
                                 rate_rps=kw.pop("rate_rps"))
    elif process == "bursty":
        times = bursty_schedule(sched_rng, duration_s, **kw)
    else:
        times = diurnal_schedule(sched_rng, duration_s, **kw)
    pop_rng = root.fork("population")
    arrivals = []
    for i, t in enumerate(times):
        fam, pid, plen, pfx, max_new = _sample_request(pop_rng, mix)
        arrivals.append(Arrival(i, t, fam, pid, plen, pfx, max_new))
    return TrafficTrace(seed, process, duration_s, tuple(arrivals))


def prompt_tokens(arr: Arrival, vocab: int = 32000):
    """Materialize the arrival's prompt as a ``(1, prompt_len)`` int32
    row. The shared-prefix portion is deterministic in ``(family,
    prefix_id)`` — every arrival in a group carries an identical real
    token prefix, so paged-KV prefix caching sees genuine reuse — and
    the suffix is deterministic in ``seq``."""
    import numpy as np

    pfx_rng = TraceRng(f"{arr.family}:{arr.prefix_id}", salt="prefix")
    sfx_rng = TraceRng(arr.seq, salt="suffix")
    row = (pfx_rng.token_row(arr.prefix_len, vocab)
           + sfx_rng.token_row(arr.prompt_len - arr.prefix_len,
                               vocab))
    return np.asarray([row], np.int32)
