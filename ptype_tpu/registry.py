"""Service registry: lease-backed discovery with watch streams.

Capability parity with the reference's ``Registry`` (cluster/registry.go:17-21):
``register`` / ``services`` / ``watch_service``, keys under
``services/<service>/<node>``, TTL-leased liveness with background keep-alive,
and watch streams with snapshot-then-delta semantics
(registry_test.go:164-190 contract).

TPU-native addition: a :class:`Node` carries the process id and **TPU device
ordinals** owned by that node, so the registry doubles as the pod's mesh map
(BASELINE.json north star: "registry.go maps actor PIDs onto TPU device
ordinals so the cluster topology *is* the pod mesh"); see
``ptype_tpu.parallel.mesh`` for the registry→Mesh lowering.
"""

from __future__ import annotations

import abc
import atexit
import json
import threading
import time

from ptype_tpu import lockcheck
import weakref
from dataclasses import dataclass, field

from ptype_tpu import chaos, logs, retry
from ptype_tpu.coord.api import CoordBackend
from ptype_tpu.coord.core import RangeOptions
from ptype_tpu.errors import CoordinationError

log = logs.get_logger("registry")

#: Every live Registration, for atexit quiescing: keepalive beats that
#: outlive the interpreter's logging teardown spew tracebacks into the
#: tail of otherwise-clean runs (daemon threads die abruptly; threads
#: mid-log die loudly). Weak so the set never keeps a handle alive.
_live_registrations: "weakref.WeakSet[Registration]" = weakref.WeakSet()


@atexit.register
def _quiesce_registrations() -> None:
    for r in list(_live_registrations):
        r._stop.set()

SERVICES_PREFIX = "services"

#: Reference hardcoded 2 s (registry.go:58-59); here it is the default,
#: overridable via platform config ``lease_ttl``.
DEFAULT_LEASE_TTL = 2.0


@dataclass(frozen=True)
class Node:
    """A registered service endpoint (ref: registry.go:23-26 + TPU fields)."""

    address: str
    port: int
    #: Host process index within the cluster (0-based).
    process_id: int = 0
    #: Global JAX device ids owned by this node's process.
    device_ordinals: tuple[int, ...] = ()
    #: Free-form extras (e.g. pipeline stage, expert group).
    metadata: dict = field(default_factory=dict, hash=False, compare=False)

    def to_json(self) -> str:
        return json.dumps(
            {
                "address": self.address,
                "port": self.port,
                "process_id": self.process_id,
                "device_ordinals": list(self.device_ordinals),
                "metadata": self.metadata,
            },
            separators=(",", ":"),
            sort_keys=True,
        )

    @staticmethod
    def from_json(raw: str) -> "Node":
        d = json.loads(raw)
        return Node(
            address=d["address"],
            port=d["port"],
            process_id=d.get("process_id", 0),
            device_ordinals=tuple(d.get("device_ordinals", ())),
            metadata=d.get("metadata", {}),
        )


def _service_key(service: str, node: str = "") -> str:
    key = f"{SERVICES_PREFIX}/{service}"
    return f"{key}/{node}" if node else key


class NodeWatch:
    """Stream of full node-set snapshots for one service.

    Contract (ref: registry.go:119-150 + registry_test.go:164-190): the
    current snapshot is delivered immediately on watch start, then a fresh
    re-listed snapshot per change. Coalescing rapid churn is the RPC
    balancer's job (debounce), not the registry's.
    """

    def __init__(self):
        self._cond = lockcheck.condition("registry.node_watch")
        self._queue: list[list[Node]] = []
        self._closed = False
        self._cancel_cb = lambda: None

    def _push(self, nodes: list[Node]) -> None:
        with self._cond:
            if self._closed:
                return
            self._queue.append(nodes)
            self._cond.notify_all()

    def get(self, timeout: float | None = None) -> list[Node] | None:
        """Next snapshot, or None on timeout/close."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._queue and not self._closed:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                self._cond.wait(remaining)
            if self._queue:
                return self._queue.pop(0)
            return None

    def latest(self, timeout: float | None = None) -> list[Node] | None:
        """Newest queued snapshot, draining any older ones — the
        consumer shape for membership-as-state users (the gateway's
        replica pool): only the CURRENT node set matters, and replaying
        a churn burst snapshot-by-snapshot would dial/evict through
        intermediate states that no longer exist. Blocks like
        :meth:`get` when the queue is empty."""
        snap = self.get(timeout=timeout)
        if snap is None:
            return None
        with self._cond:
            if self._queue:
                snap = self._queue[-1]
                self._queue.clear()
        return snap

    def cancel(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._cancel_cb()

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def __iter__(self):
        while True:
            snap = self.get()
            if snap is None and self.closed:
                return
            if snap is not None:
                yield snap


class Registration:
    """Handle for a live registration; owns the lease keep-alive loop."""

    def __init__(self, registry: "CoordRegistry", service: str, node: str,
                 lease_id: int, ttl: float, node_json: str):
        self._registry = registry
        self.service = service
        self.node = node
        self.lease_id = lease_id
        self.ttl = ttl
        self._node_json = node_json
        self._stop = threading.Event()
        self._failures = 0
        # The loop holds only a WEAK reference to this handle between
        # beats: an abandoned Registration (a crash simulation's `del`,
        # a test that leaked one) becomes garbage, and its thread exits
        # on the next beat instead of heartbeating — and warning —
        # forever. A bound-method target would pin the handle alive.
        self._thread = threading.Thread(
            target=Registration._keepalive_entry,
            args=(weakref.ref(self), self._stop, ttl / 2.0),
            name=f"lease-keepalive-{service}/{node}",
            daemon=True,
        )
        self._thread.start()
        _live_registrations.add(self)

    @staticmethod
    def _keepalive_entry(ref: "weakref.ref[Registration]",
                         stop: threading.Event, interval: float) -> None:
        # Refresh at half the TTL, the usual heartbeat cadence
        # (ref: clientv3 KeepAlive drained in a goroutine, registry.go:69-83).
        while not stop.wait(interval):
            self = ref()
            if self is None:
                return  # handle was abandoned; nothing left to keep alive
            self._keepalive_once(stop)
            del self  # drop the strong ref before parking on the event

    def _keepalive_once(self, stop: threading.Event) -> None:
        if getattr(self._registry._coord, "closed", False):
            # Checked unconditionally, not just on error: a closed
            # LocalCoord's state still ANSWERS keepalives (close()
            # stops the sweeper but keeps leases), so an exception-path
            # check would never fire there and the loop would heartbeat
            # a closed state forever.
            log.debug("keepalive stopping: coordination client closed",
                      kv={"service": self.service, "node": self.node})
            stop.set()
            return
        try:
            self._registry._coord.keepalive(self.lease_id)
            if self._failures:
                log.info("lease refresh recovered",
                         kv={"service": self.service, "node": self.node})
            self._failures = 0
            log.debug("lease refreshed",
                      kv={"service": self.service, "node": self.node})
        except CoordinationError as e:
            if getattr(self._registry._coord, "closed", False):
                # Closed for good mid-flight; next beat exits via the
                # unconditional check — just don't warn about it.
                stop.set()
                return
            self._failures += 1
            if self._failures <= 3 or self._failures % 10 == 0:  # bound spam
                log.warning("lease refresh failed",
                            kv={"service": self.service, "node": self.node,
                                "err": str(e), "failures": self._failures})
            # If the lease itself is gone (expired server-side during a
            # partition), a retry can never succeed — re-register with a
            # fresh lease instead of heartbeating a dead registration.
            if "not found" in str(e).lower():
                self._reregister()

    def _reregister(self) -> None:
        # A close() racing with an in-flight keepalive must not resurrect
        # the registration with a fresh lease after the deliberate revoke.
        if self._stop.is_set():
            return
        try:
            lease_id = self._registry._coord.grant(self.ttl)
            self._registry._coord.put(
                _service_key(self.service, self.node), self._node_json,
                lease=lease_id,
            )
            self.lease_id = lease_id
            chaos.note_ok("coord.lease",
                          f"{self.service}/{self.node}")
            log.info("re-registered after lease loss",
                     kv={"service": self.service, "node": self.node,
                         "lease": lease_id})
        except CoordinationError as e:
            log.warning("re-registration failed",
                        kv={"service": self.service, "node": self.node,
                            "err": str(e)})

    def close(self, revoke: bool = True) -> None:
        """Stop keeping the registration alive.

        ``revoke=True`` deregisters immediately (an intentional fix over the
        reference, which only ever let the lease lapse — SURVEY.md §2).
        ``revoke=False`` abandons the lease so liveness expiry does the work,
        which is what a crashed process looks like.
        """
        self._stop.set()
        if revoke:
            try:
                self._registry._coord.revoke(self.lease_id)
            except CoordinationError:
                pass


class Registry(abc.ABC):
    """The mockable seam the reference's tests relied on (SURVEY.md §4)."""

    @abc.abstractmethod
    def register(self, service_name: str, node_name: str, host: str,
                 port: int, *, process_id: int = 0,
                 device_ordinals: tuple[int, ...] = (),
                 metadata: dict | None = None) -> Registration: ...

    @abc.abstractmethod
    def services(self) -> dict[str, list[Node]]: ...

    @abc.abstractmethod
    def watch_service(self, service_name: str) -> NodeWatch: ...


class CoordRegistry(Registry):
    """Registry over a coordination backend (the etcdRegistry analog)."""

    def __init__(self, coord: CoordBackend, lease_ttl: float = DEFAULT_LEASE_TTL):
        self._coord = coord
        self._lease_ttl = lease_ttl

    def register(self, service_name: str, node_name: str, host: str,
                 port: int, *, process_id: int = 0,
                 device_ordinals: tuple[int, ...] = (),
                 metadata: dict | None = None) -> Registration:
        node = Node(
            address=host,
            port=port,
            process_id=process_id,
            device_ordinals=tuple(device_ordinals),
            metadata=metadata or {},
        )
        lease_id = self._coord.grant(self._lease_ttl)
        self._coord.put(
            _service_key(service_name, node_name), node.to_json(), lease=lease_id
        )
        log.info("registered service node",
                 kv={"service": service_name, "node": node_name,
                     "addr": f"{host}:{port}",
                     "devices": list(device_ordinals)})
        return Registration(self, service_name, node_name, lease_id,
                            self._lease_ttl, node.to_json())

    def services(self) -> dict[str, list[Node]]:
        res = self._coord.range(
            SERVICES_PREFIX + "/", RangeOptions(prefix=True)
        )
        out: dict[str, list[Node]] = {}
        for item in res.items:
            parts = item.key.split("/")
            if len(parts) < 3:
                continue
            service = parts[1]
            try:
                out.setdefault(service, []).append(Node.from_json(item.value))
            except (json.JSONDecodeError, KeyError):
                log.warning("skipping malformed registry entry",
                            kv={"key": item.key})
        for nodes in out.values():
            nodes.sort(key=lambda n: (n.address, n.port))
        return out

    def nodes(self, service_name: str) -> list[Node]:
        res = self._coord.range(
            _service_key(service_name) + "/", RangeOptions(prefix=True)
        )
        nodes = []
        for item in res.items:
            try:
                nodes.append(Node.from_json(item.value))
            except (json.JSONDecodeError, KeyError):
                log.warning("skipping malformed registry entry",
                            kv={"key": item.key})
        nodes.sort(key=lambda n: (n.address, n.port))
        return nodes

    def watch_service(self, service_name: str) -> NodeWatch:
        nw = NodeWatch()
        coord_watch = self._coord.watch(_service_key(service_name) + "/")
        nw._cancel_cb = coord_watch.cancel

        def pump():
            # Initial snapshot first (registry_test.go:164-190 contract),
            # then one re-listed snapshot per event batch. A re-list that
            # dies mid-flight (coordinator failover, reconnect racing the
            # call) is TRANSIENT: retry it — terminating here killed the
            # NodeWatch forever while the underlying coord watch went on
            # to be re-armed. The pump ends only when the NodeWatch or
            # the coord watch is deliberately closed.
            need_list = True
            epoch = getattr(coord_watch, "epoch", 0)
            bo = retry.Backoff(base=0.3, cap=1.0)
            try:
                while not nw.closed and not coord_watch.closed:
                    if need_list:
                        try:
                            nw._push(self.nodes(service_name))
                        except CoordinationError as e:
                            if getattr(self._coord, "closed", False):
                                # Closed for good: the reader has (or
                                # will) cancel the coord watch; exit
                                # quietly instead of warn-spinning.
                                return
                            log.warning(
                                "service watch re-list failed; retrying",
                                kv={"service": service_name,
                                    "err": str(e)})
                            bo.sleep()
                            continue
                        need_list = False
                        bo.reset()
                    if coord_watch.get(timeout=0.5):
                        need_list = True
                    # A re-armed watch (reconnect) missed the outage's
                    # events — resync with a fresh list.
                    new_epoch = getattr(coord_watch, "epoch", 0)
                    if new_epoch != epoch:
                        epoch = new_epoch
                        need_list = True
            finally:
                nw.cancel()

        threading.Thread(
            target=pump, name=f"watch-{service_name}", daemon=True
        ).start()
        return nw
