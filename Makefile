# Mirrors the reference's Makefile contract (race-enabled full suite with a
# wall-clock budget, Makefile:1-6) — Python's analog: the full suite on the
# virtual 8-device CPU mesh with a hard timeout.

.PHONY: test bench lint native tpu-smoke tpu-validate

test:
	python -m pytest tests/ -x -q

bench:
	python bench.py

# Compile + run the Pallas flash kernel fwd/bwd on an attached TPU —
# the only tier that sees Mosaic tiling checks (exit 42 = no TPU,
# treated as skip, not failure).
tpu-smoke:
	python tests/tpu_smoke.py || test $$? -eq 42

# Full hardware revalidation after a tunnel outage / kernel change:
# the Mosaic-visible smoke (flash fwd+bwd, MoE step, KV-cache
# generate), then the headline bench JSON line.
tpu-validate: tpu-smoke bench

lint:
	python -m compileall -q ptype_tpu

# Native wire transport (writev frame sends, GIL-free reads, crc32c).
# ptype_tpu.native also builds this lazily on first load.
native:
	g++ -O3 -fPIC -shared -o ptype_tpu/_ptype_wire.so native/ptype_wire.cpp
