# Mirrors the reference's Makefile contract (race-enabled suite with a
# wall-clock budget, Makefile:1-6). `test` is the fast tier — the
# control-plane/unit surface, the analog of the reference's 35 s suite;
# `test-all` adds the XLA-compile-heavy ML tests and the multiprocess/
# failover/scale drills (the `slow` marker, tests/conftest.py).

.PHONY: test test-all bench serve-bench spec-bench disagg-bench scale-bench traffic-bench collectives-bench hier-bench zero-bench profile-bench jitwatch-bench lint native tpu-smoke tpu-validate chaos obs-demo health-demo serve-obs-demo

test:
	python -m pytest tests/ -x -q -m "not slow"

test-all:
	python -m pytest tests/ -q

bench:
	python bench.py

# Serving tail-latency microbench through the inference gateway
# (docs/OPERATIONS.md "Serving at scale"): three replicas, one slow;
# the JSON tail carries serve_p99_ms / serve_tokens_per_sec via the
# gateway and the round-robin comparison p99, plus the paged-engine
# probe's serve_prefix_hit_speedup / serve_kv_util_pct /
# serve_prefill_stall_ms (shared-prefix workload, affinity-routed,
# chunked admission — the ISSUE 9 acceptance numbers).
serve-bench:
	JAX_PLATFORMS=cpu python bench.py --serve

# Speculative-decoding microbench (docs/PERF.md "Speculative
# decoding"): batch-1 single-stream decode tokens/sec through the
# paged engine with draft-propose + batched target-verify vs the
# plain engine, at bit-identical greedy output, plus the measured
# accept rate — the ISSUE 12 acceptance numbers. Also emitted in the
# serve-bench tail.
spec-bench:
	JAX_PLATFORMS=cpu python bench.py --spec

# Disaggregated-serving microbench (docs/OPERATIONS.md
# "Disaggregated serving"): the same mixed long-prompt/short-decode
# load through an interleaved fleet vs a prefill+decode split with
# KV-block migration — the JSON tail carries disagg_ttft_p99_ms vs
# interleaved_ttft_p99_ms (prefill isolation must win),
# migrate_ms_per_block (q8 wire) and migrate_dedup_ratio (chain-hash
# manifest on a shared-prefix family) — the ISSUE 16 acceptance
# numbers.
disagg-bench:
	JAX_PLATFORMS=cpu python bench.py --disagg

# Elastic-reconciler microbench (docs/OPERATIONS.md "Elastic
# serving"): a reconciler-managed fleet behind the gateway — the JSON
# tail carries scale_up_latency_s (first shed -> new replica
# answering, the spike-to-capacity lag) and drain_lost_requests
# (graceful drain under continuous traffic; the bar is 0) — the
# ISSUE 13 acceptance numbers.
scale-bench:
	JAX_PLATFORMS=cpu python bench.py --scale

# Open-loop traffic observatory (docs/OBSERVABILITY.md "Traffic
# plane", docs/OPERATIONS.md "Capacity planning"): one seeded trace
# replayed open-loop at >= 5 offered rates through the gateway +
# reconciler fleet — the JSON tail carries the capacity frontier with
# its located knee (traffic_knee_rps / traffic_goodput_at_knee_pct /
# traffic_ttft_p99_ms_open_loop), the diurnal-spike drill (the
# reconciler-armed fleet must hold the TTFT p99 SLO through the
# replayed spike the static fleet fails), scale-up-latency vs burst
# steepness, and the shed-rate-vs-burn-budget curve — the ISSUE 19
# acceptance numbers. Replay any run with PTYPE_TRAFFIC_SEED=<seed>.
traffic-bench:
	JAX_PLATFORMS=cpu python bench.py --traffic

# Gradient-wire microbench on the 8-device virtual host mesh
# (docs/PERF.md "Quantized + overlapped collectives"): bucketed
# allreduce GB/s per wire format (fp32 / per-chunk int8 / block-scaled
# int8 sweep), quantized push_tree timing, and the goodput ledger's
# collective share of store-DP step time with fine-grained overlap
# off vs on (the ISSUE 6 acceptance numbers).
collectives-bench:
	JAX_PLATFORMS=cpu XLA_FLAGS="$(XLA_FLAGS) --xla_force_host_platform_device_count=8" \
		python bench.py --collectives

# Hierarchical-collectives microbench on the 8-device emulated
# asymmetric host mesh (docs/PERF.md "Hierarchical collectives"):
# hierarchical vs flat bucketed-allreduce step time at exact-wire
# parity for every (outer, inner) factorization of 8, the measured
# slow-leg wire bytes (acceptance: <= 1/n_inner of the flat outer
# footprint), and the per-leg bandwidth model's pricing of the
# emulated ICI/DCN asymmetry (the ISSUE 18 numbers).
hier-bench:
	JAX_PLATFORMS=cpu XLA_FLAGS="$(XLA_FLAGS) --xla_force_host_platform_device_count=8" \
		python bench.py --hier

# ZeRO-1 sharded-optimizer microbench on the 8-device virtual host
# mesh (docs/PERF.md "Sharded optimizer update (ZeRO-1)"): per-replica
# optimizer-state bytes and step time for zero=True vs the replicated
# store-DP baseline (exact + int8/EF wires), plus the goodput ledger's
# optimizer_ms leg — the ISSUE 7 acceptance numbers.
zero-bench:
	JAX_PLATFORMS=cpu XLA_FLAGS="$(XLA_FLAGS) --xla_force_host_platform_device_count=8" \
		python bench.py --zero

# Profiling-plane microbench on the 8-device virtual host mesh
# (docs/OBSERVABILITY.md "Profiling plane"): the capture-disabled
# overhead of the armed plane on the store-DP loop (<1% acceptance),
# the live-capture step cost, and the compiled-vs-analytic FLOPs gap
# on the 125M config (XLA cost_analysis, layer scan unrolled) — the
# ISSUE 8 acceptance numbers.
profile-bench:
	JAX_PLATFORMS=cpu XLA_FLAGS="$(XLA_FLAGS) --xla_force_host_platform_device_count=8" \
		python bench.py --profile

# Recompile-watchdog microbench (docs/LINTING.md "The runtime half"):
# the armed jitwatch hot-region price — transfer-guard entry per
# dispatch, charged against an engine-shaped step with its one host
# sync per iteration (<5% acceptance bar), plus a
# zero-steady-state-recompiles check on the probe itself — the
# ISSUE 15 acceptance numbers. Also emitted in the headline bench
# tail as jitwatch_overhead_pct.
jitwatch-bench:
	JAX_PLATFORMS=cpu python bench.py --jitwatch

# Seeded chaos soak (docs/OPERATIONS.md "Chaos drills"): a FRESH random
# fault schedule against the in-process trainer + registry +
# coordinator stack every run. On failure the harness prints the
# FaultPlan JSON; replay the exact schedule with
# PTYPE_CHAOS_SOAK_SEED=<seed> make chaos.
chaos:
	PTYPE_CHAOS_SOAK_SEED=$${PTYPE_CHAOS_SOAK_SEED:-$$(date +%s)} \
		python -m pytest tests/test_chaos_soak.py -q

# Distributed-tracing walkthrough (docs/OBSERVABILITY.md): a traced
# in-process fleet (coordinator + two workers over real sockets +
# gateway) serves a few requests — one under a seeded chaos fault —
# then the cluster telemetry snapshot is pulled over actor RPC and a
# stitched Chrome trace (Perfetto-loadable) is written.
obs-demo:
	JAX_PLATFORMS=cpu python examples/observability/demo.py

# Cluster health plane walkthrough (docs/OBSERVABILITY.md "Health
# plane & alerting"): a simulated 3-worker fleet with per-node goodput
# ledgers + samplers, a seeded chaos straggler fault on one worker's
# store.push — the alert engine names the afflicted node from the
# stitched cluster snapshot and the `obs top` view renders it.
health-demo:
	JAX_PLATFORMS=cpu python examples/observability/health_demo.py

# Serving observability walkthrough (docs/OBSERVABILITY.md "Serving
# plane"): a traced 2-replica paged fleet takes a shared-prefix burst
# through the gateway; the serving ledger's TTFT/TPOT/KV series feed
# the `obs serve` view and one stitched Perfetto export lands in
# $OBS_DIR/serve_trace.json.
serve-obs-demo:
	JAX_PLATFORMS=cpu python examples/observability/serve_demo.py

# Compile + run the Pallas flash kernel fwd/bwd on an attached TPU —
# the only tier that sees Mosaic tiling checks (exit 42 = no TPU,
# treated as skip, not failure).
tpu-smoke:
	python tests/tpu_smoke.py || test $$? -eq 42

# Full hardware revalidation after a tunnel outage / kernel change:
# the Mosaic-visible smoke (flash fwd+bwd, MoE step, KV-cache
# generate), then the headline bench JSON line.
tpu-validate: tpu-smoke bench

# PERF.md refresh rows (headline, S=8192, decode, store-vs-gspmd) as
# a markdown table; exit 42 when no TPU (use --smoke off-TPU).
tpu-sweep:
	python tools/tpu_sweep.py || test $$? -eq 42

# Real static analysis (reference bar: golangci-lint, .golangci.yml):
# the stdlib-only ptlint package (tools/ptlint) — the pyflakes-grade
# base checks plus the PT001–PT017 house rules (catalogue:
# docs/LINTING.md; suppressions are `# ptlint: disable=PTxxx -- why`
# and MUST carry the justification). Also invoked from the tier-1
# suite with a <10 s wall budget (tests/test_ptlint.py), so a broken
# or slow linter fails `make test` too.
lint:
	python -m tools.ptlint ptype_tpu tools tests examples bench.py __graft_entry__.py
	python -m compileall -q ptype_tpu

# Native wire transport (writev frame sends, GIL-free reads, crc32c).
# ptype_tpu.native also builds this lazily on first load.
native:
	g++ -O3 -fPIC -shared -o ptype_tpu/_ptype_wire.so native/ptype_wire.cpp
