# Mirrors the reference's Makefile contract (race-enabled full suite with a
# wall-clock budget, Makefile:1-6) — Python's analog: the full suite on the
# virtual 8-device CPU mesh with a hard timeout.

.PHONY: test bench lint

test:
	python -m pytest tests/ -x -q

bench:
	python bench.py

lint:
	python -m compileall -q ptype_tpu
