"""Headline benchmark: optimus-125M data-parallel training throughput.

Prints JSON lines; the LAST line is the record:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``

The metric is tokens/sec/chip on the north-star config (BASELINE.json:
"optimus-125M tokens/sec/chip"); ``vs_baseline`` is achieved MFU divided
by the 0.30 MFU target (the only quantitative baseline the reference
world defines — SURVEY.md §6: the reference publishes no numbers).

Reliability contract (VERDICT r3 weak #1: three rounds of empty tails):

- A provisional labeled JSON line is emitted AND FLUSHED before any
  device work, and an updated line after every attempt — a driver kill
  at any moment leaves a labeled record in the tail, never emptiness.
- Worst-case wall clock is bounded at ~15 min: backend probe <=60 s,
  TPU attempts at <=360 s / <=240 s, CPU fallback <=180 s. If the probe
  hangs (wedged tunnel), the CPU fallback runs FIRST so a real number
  lands early, then one short TPU attempt still runs in case the
  tunnel returned mid-bench.
- The measurement runs in a fresh ``--worker`` subprocess — JAX caches
  backend-init *failure* in-process, so retries only mean anything in a
  new interpreter.
- ``store_allreduce_gbps`` (the second BASELINE metric) is always
  populated: over ICI when >1 chip, else over an 8-device virtual host
  mesh (labeled as such — a single v5e chip has no ICI to measure).
- ``store_push_tree_ms`` reports the bucketed whole-param-tree Store
  push (one fused collective per bucket; parallel/collectives.py
  bucketing layer), with the per-leaf time in its note for the
  speedup ratio — filled from the same host-mesh stand-in on 1 chip.
- ``trace_overhead_pct`` reports the distributed-tracing cost on the
  host-mesh store-DP step loop (ptype_tpu.telemetry
  .measure_trace_overhead): traced vs untraced wall clock, plus the
  measured disabled-hook cost in its note — the trace plane's
  ~zero-cost contract as a number (acceptance: <1% disabled, <5%
  enabled).
- ``goodput_pct`` / ``step_breakdown`` / ``sampler_overhead_pct``
  come from the health plane's goodput ledger on the same host-mesh
  store-DP loop (ptype_tpu.health.bench.measure_health_overhead):
  live compute/collective/data/stall attribution per step, plus the
  measured sampler tick cost as a fraction of its cadence (ISSUE 5
  acceptance: <1% of step time).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

MFU_TARGET = 0.30  # BASELINE.json north_star: ">=30% MFU on v5e-8"

#: Probe cap: a healthy backend answers jax.devices() in ~5-20 s; the
#: observed wedged-tunnel mode hangs indefinitely.
PROBE_TIMEOUT = 60
#: First TPU attempt (full 5-rung ladder; healthy path is ~2-3 min).
ATTEMPT_TIMEOUT = 360
#: Second TPU attempt — dense-xla rungs only after a timeout (a
#: hang-mode flash regression hangs again; don't re-burn the budget).
RETRY_TIMEOUT = 240
#: CPU smoke fallback (tiny preset; seconds of compute + init).
CPU_TIMEOUT = 180
#: Host-mesh store probe (8 virtual CPU devices): allreduce GB/s plus
#: the bucketed push_tree timing (compiles both push paths).
STORE_PROBE_TIMEOUT = 240


# ----------------------------------------------------------------- worker


def _run(cfg, devices, per_chip_batch, seq, steps, warmup):
    import jax

    from ptype_tpu.parallel.mesh import build_mesh
    from ptype_tpu.train.data import synthetic_batches
    from ptype_tpu.train.trainer import Trainer

    n_chips = len(devices)
    mesh = build_mesh({"data": n_chips}, devices=devices)
    trainer = Trainer(cfg, mesh, sync_every=0)
    batch = per_chip_batch * n_chips
    stream = synthetic_batches(cfg.vocab_size, batch, seq)

    for _ in range(warmup):
        out = trainer.step(next(stream))
    trainer.sync()  # compile + warmup fully drained before the clock

    t0 = time.perf_counter()
    tokens = 0
    for _ in range(steps):
        out = trainer.step(next(stream))
        tokens += batch * seq
    jax.block_until_ready(out["loss"])  # steps dispatch async; drain
    dt = time.perf_counter() - t0
    return out, tokens, dt


def worker_main() -> None:
    import jax

    from ptype_tpu.models import transformer as tfm

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    n_chips = len(devices)

    # (per-chip batch, seq, steps, warmup, remat, attn). Flash attention
    # leads the ladder (activation memory linear in S; larger batches
    # feed the MXU) but the LAST rung is attn_impl="xla": a flash-kernel
    # regression must degrade to a dense-attention baseline number, never
    # zero the round (VERDICT r2 weak #2 — round 2 emitted nothing
    # because every rung shared the one broken kernel).
    # remat is "dots" | True | False: "dots" = jax.checkpoint with the
    # dots-saveable policy — the round-3 sweep's best plan (0.448 MFU
    # vs 0.445 no-remat, 0.434 b=24, 0.328 scan_unroll=2; b=32 no-remat
    # crashes the v5e remote-compile helper, which is why the b=16
    # rung leads).
    if on_tpu:
        preset_name = "optimus-125m"
        plans = [(16, 1024, 30, 3, "dots", "flash"),
                 (16, 1024, 30, 3, False, "flash"),
                 (8, 1024, 20, 3, True, "flash"),
                 (16, 1024, 30, 3, False, "xla"),
                 (8, 1024, 20, 3, True, "xla")]
    else:
        preset_name = "tiny"
        plans = [(4, 128, 5, 1, False, "xla")]
    # A hang-mode flash regression times out the whole attempt before
    # the dense rungs run; the orchestrator retries with this env set so
    # the retry starts at the xla rungs instead of hanging again.
    if os.environ.get("PTYPE_BENCH_ATTN") == "xla":
        plans = [p for p in plans if p[5] == "xla"] or plans

    # The bench runs unattended: fall back to smaller batches (and remat
    # as a last resort) rather than dying on an HBM OOM.
    last_err = None
    for pcb, seq, steps, warmup, remat, attn in plans:
        try:
            cfg = tfm.preset(
                preset_name, remat=bool(remat), attn_impl=attn,
                remat_policy="dots" if remat == "dots" else "none")
            out, tokens, dt = _run(cfg, devices, pcb, seq, steps, warmup)
            batch_used, seq_used, attn_used = pcb * n_chips, seq, attn
            remat_used = remat
            break
        except Exception as e:  # noqa: BLE001 — report, try next plan
            last_err = e
    else:
        print(json.dumps({
            "metric": "optimus-125M tokens/sec/chip",
            "value": None, "unit": "tokens/sec/chip", "vs_baseline": None,
            "error": f"all plans failed: {last_err!r:.500}",
        }), flush=True)
        raise SystemExit(3)

    tps_chip = tokens / dt / n_chips
    from ptype_tpu.metrics import device_peak_tflops, mfu as mfu_of

    achieved_mfu = mfu_of(
        tokens / dt, tfm.flops_per_token(cfg, seq_used), n_chips,
        device_peak_tflops(devices[0]),
    )

    # Second BASELINE metric: Store push/pull == allreduce bandwidth.
    # >1 chip: measured here over the real mesh. 1 chip: left null and
    # filled by the orchestrator's host-mesh probe (labeled) — a single
    # chip has no ICI, but the round record must not carry a bare null
    # (VERDICT r3 item 1).
    store_gbps = None
    store_note = None
    if n_chips > 1:
        from ptype_tpu.parallel.collectives import measure_allreduce_gbps
        from ptype_tpu.parallel.mesh import build_mesh

        try:
            store_gbps = round(measure_allreduce_gbps(
                build_mesh({"data": n_chips}, devices=devices),
                mbytes=64 if on_tpu else 4), 2)
        except Exception as e:  # noqa: BLE001 — secondary, best-effort
            store_note = f"failed: {e!r:.200}"
    record = {
        "metric": "optimus-125M tokens/sec/chip"
        if on_tpu else "optimus-tiny tokens/sec/chip (cpu smoke)",
        "value": round(tps_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(achieved_mfu / MFU_TARGET, 4),
        "mfu": round(achieved_mfu, 4),
        "attn": attn_used,
        "remat": str(remat_used),
        "n_chips": n_chips,
        "batch": batch_used,
        "seq": seq_used,
        "store_allreduce_gbps": store_gbps,
        "store_allreduce_note": store_note,
        "store_push_tree_ms": None,
        "store_push_tree_note": (
            "bucketed probe did not complete" if n_chips > 1 else None),
        "trace_overhead_pct": None,
        "trace_overhead_note": None,
        "goodput_pct": None,
        "step_breakdown": None,
        "sampler_overhead_pct": None,
        "health_note": None,
        "store_wire_gbps": None,
        "store_wire_note": None,
        "collective_overlap_pct": None,
        "collective_note": None,
        "zero_opt_mem_mb": None,
        "zero_step_ms": None,
        "zero_note": None,
        "zero2_grad_mem_mb": None,
        "zero3_param_mem_mb": None,
        "zero_ladder_note": None,
        "reshard_resume_steps": None,
        "reshard_note": None,
        "profile_overhead_pct": None,
        "profile_note": None,
        "lockcheck_overhead_pct": None,
        "lockcheck_note": None,
        "jitwatch_overhead_pct": None,
        "jitwatch_note": None,
        "compiled_flops_per_token": None,
        "compiled_flops_note": None,
        "final_loss": round(float(out["loss"]), 4),
    }
    # The primary metric is EARNED at this point — print it before the
    # heavyweight push-tree probe so a wedged probe (the observed
    # tunnel hang mode blocks, it doesn't raise) can't destroy the
    # training result; a completed probe supersedes with a second line.
    print(json.dumps(record), flush=True)
    if n_chips > 1:
        # Bucketed whole-tree push: the metric the bucketing layer
        # exists for (one fused launch per bucket vs one per leaf).
        try:
            from ptype_tpu.parallel.tensorstore import measure_push_tree

            r = measure_push_tree(
                build_mesh({"data": n_chips}, devices=devices),
                preset=preset_name, iters=2)
            record["store_push_tree_ms"] = r["bucketed_ms"]
            record["store_push_tree_note"] = (
                f"per-leaf {r['per_leaf_ms']} ms ({r['speedup']}x), "
                f"{r['n_buckets']} buckets / {r['n_leaves']} leaves, "
                f"{r['gbps']} GB/s")
        except Exception as e:  # noqa: BLE001 — secondary, best-effort
            record["store_push_tree_note"] = f"failed: {e!r:.200}"
        print(json.dumps(record), flush=True)


# ------------------------------------------------------------ orchestrator


def _mesh_geometry() -> dict:
    """Mesh geometry stamped on every tail record (ISSUE 18) so
    numbers are comparable across runs: outer×inner + the emulated
    bandwidth ratio when ``PTYPE_TOPOLOGY`` names a hierarchy, a flat
    marker otherwise. Env-gated so the orchestrator's early
    provisional emit never pays a jax import."""
    if not os.environ.get("PTYPE_TOPOLOGY"):
        return {"topology": "flat"}
    try:
        from ptype_tpu.parallel.topology import Topology

        topo = Topology.from_env()
        return topo.describe() if topo else {"topology": "flat"}
    except Exception as e:  # noqa: BLE001
        return {"topology": f"unparsed ({e})"}


def _emit(rec: dict) -> None:
    if "metric" in rec and "mesh_geometry" not in rec:
        rec["mesh_geometry"] = _mesh_geometry()
    print(json.dumps(rec), flush=True)


def _attempt(extra_env: dict | None = None,
             timeout: int = ATTEMPT_TIMEOUT) -> tuple[str | None, str, bool]:
    """Run one fresh worker process.

    Returns (json_line | None, err_tail, fatal). ``fatal`` means the
    worker ran to a structured verdict (rc=3: every plan failed
    deterministically) — retrying the identical ladder cannot help, and
    the worker's own JSON error line is the authoritative record.
    """
    env = dict(os.environ)
    if extra_env:
        env.update(extra_env)
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            capture_output=True, text=True, timeout=timeout,
            env=env,
        )
    except subprocess.TimeoutExpired as te:
        # The worker prints its earned record BEFORE the secondary
        # push-tree probe — salvage it rather than discarding a real
        # measurement because a best-effort probe wedged.
        out = te.stdout or ""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        salvaged = [ln for ln in out.splitlines()
                    if ln.startswith("{") and '"metric"' in ln]
        if salvaged:
            return salvaged[-1], (
                f"worker timed out after {timeout}s; salvaged its last "
                "record"), False
        return None, f"worker timed out after {timeout}s", False
    lines = [ln for ln in p.stdout.splitlines()
             if ln.startswith("{") and '"metric"' in ln]
    if p.returncode == 0 and lines:
        return lines[-1], "", False
    if p.returncode == 3 and lines:
        return lines[-1], "worker: all plans failed", True
    tail = (p.stderr or p.stdout or "").strip().splitlines()[-6:]
    return None, " | ".join(tail)[-800:], False


def _backend_probe(timeout: int = PROBE_TIMEOUT) -> bool:
    """True when the accelerator backend initializes in a fresh
    process. A wedged device tunnel HANGS backend init (observed on
    this harness for hours); without this probe every ladder attempt
    would burn its full budget discovering the same hang, and the
    driver's own cap could zero the round before the CPU fallback."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, timeout=timeout, env=dict(os.environ))
        return p.returncode == 0
    except subprocess.TimeoutExpired:
        return False


_HOSTMESH_LABEL = "8-device virtual host mesh (single chip: no ICI)"


def _hostmesh_probe(code: str, timeout: int) -> tuple[dict | None, str]:
    """Run one JSON-emitting probe snippet on an 8-device virtual host
    mesh in a fresh CPU-pinned subprocess."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        p = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return None, "host-mesh probe timed out"
    if p.returncode != 0:
        tail = (p.stderr or "").strip().splitlines()[-2:]
        return None, f"host-mesh probe failed: {' | '.join(tail)[-200:]}"
    try:
        return json.loads(p.stdout.strip().splitlines()[-1]), \
            _HOSTMESH_LABEL
    except (ValueError, IndexError):
        return None, f"host-mesh probe bad output: {p.stdout[-120:]!r}"


def _store_gbps_hostmesh() -> tuple[float | None, str]:
    """Store allreduce bandwidth over the virtual host mesh — its OWN
    subprocess, so the 'always populated' contract on the second
    BASELINE metric (VERDICT r3 item 1) cannot be broken by a failure
    in the newer push-tree probe."""
    probe, note = _hostmesh_probe(
        "import json\n"
        "from ptype_tpu.parallel.collectives import measure_allreduce_gbps\n"
        "from ptype_tpu.parallel.mesh import build_mesh\n"
        "print(json.dumps({'gbps': round(measure_allreduce_gbps("
        "build_mesh({'data': 8}), mbytes=16), 2)}))\n",
        STORE_PROBE_TIMEOUT)
    return (probe["gbps"] if probe else None), note


def _push_tree_hostmesh() -> tuple[dict | None, str]:
    """Bucketed vs per-leaf push_tree timing over the virtual host
    mesh (tiny preset; compiles both push paths)."""
    return _hostmesh_probe(
        "import json\n"
        "from ptype_tpu.parallel.tensorstore import measure_push_tree\n"
        "from ptype_tpu.parallel.mesh import build_mesh\n"
        "print(json.dumps(measure_push_tree("
        "build_mesh({'data': 8}), preset='tiny', iters=2)))\n",
        STORE_PROBE_TIMEOUT)


def _trace_overhead_hostmesh() -> tuple[dict | None, str]:
    """Traced vs untraced store-DP step loop over the virtual host
    mesh — fills ``trace_overhead_pct`` (the trace plane's measured
    cost; ISSUE 4 acceptance: <1% disabled, <5% enabled)."""
    return _hostmesh_probe(
        "import json\n"
        "from ptype_tpu.telemetry import measure_trace_overhead\n"
        "print(json.dumps(measure_trace_overhead()))\n",
        STORE_PROBE_TIMEOUT)


def _wire_hostmesh() -> tuple[dict | None, str]:
    """Bucketed-allreduce bandwidth per wire format (fp32 vs PR 1's
    per-chunk int8 vs the block-scaled int8 sweep) over the virtual
    host mesh — fills ``store_wire_gbps`` (ISSUE 6)."""
    return _hostmesh_probe(
        "import json\n"
        "from ptype_tpu.parallel.collectives import measure_wire_gbps\n"
        "from ptype_tpu.parallel.mesh import build_mesh\n"
        "print(json.dumps(measure_wire_gbps(build_mesh({'data': 8}),"
        " mbytes=16, iters=3)))\n",
        STORE_PROBE_TIMEOUT)


def _overlap_hostmesh() -> tuple[dict | None, str]:
    """Store-DP collective share, synchronous baseline vs fine-grained
    overlap — fills ``collective_overlap_pct`` (ISSUE 6 acceptance:
    the goodput ledger's collective leg shrinks with overlap on)."""
    return _hostmesh_probe(
        "import json\n"
        "from ptype_tpu.parallel.mesh import build_mesh\n"
        "from ptype_tpu.train.store_dp import measure_overlap\n"
        "print(json.dumps(measure_overlap(build_mesh({'data': 8}),"
        " steps=6)))\n",
        STORE_PROBE_TIMEOUT)


def _zero_hostmesh() -> tuple[dict | None, str]:
    """ZeRO-1 sharded optimizer update vs the replicated store-DP
    baseline — fills ``zero_opt_mem_mb`` / ``zero_step_ms`` (ISSUE 7
    acceptance: per-replica optimizer bytes shrink ~N× at matched
    loss)."""
    return _hostmesh_probe(
        "import json\n"
        "from ptype_tpu.parallel.mesh import build_mesh\n"
        "from ptype_tpu.train.store_dp import measure_zero\n"
        "print(json.dumps(measure_zero(build_mesh({'data': 8}),"
        " steps=6)))\n",
        STORE_PROBE_TIMEOUT)


def _zero_ladder_hostmesh() -> tuple[dict | None, str]:
    """The full ZeRO ladder (ISSUE 17): per-replica resident bytes for
    moments / grads / params at stages 0-3 — fills
    ``zero2_grad_mem_mb`` / ``zero3_param_mem_mb``."""
    return _hostmesh_probe(
        "import json\n"
        "from ptype_tpu.parallel.mesh import build_mesh\n"
        "from ptype_tpu.train.store_dp import measure_zero_ladder\n"
        "print(json.dumps(measure_zero_ladder(build_mesh({'data': 8}),"
        " steps=3)))\n",
        STORE_PROBE_TIMEOUT)


def _reshard_hostmesh() -> tuple[dict | None, str]:
    """Live mid-run reshard 8→4 vs the checkpoint-restore round trip
    (ISSUE 17) — fills ``reshard_resume_steps`` (recovery wall time in
    steady-step units)."""
    return _hostmesh_probe(
        "import json\n"
        "from ptype_tpu.train.store_dp import measure_reshard\n"
        "print(json.dumps(measure_reshard(steps=3)))\n",
        STORE_PROBE_TIMEOUT)


def _profile_hostmesh() -> tuple[dict | None, str]:
    """Capture-disabled cost of the profiling plane on the host-mesh
    store-DP loop — fills ``profile_overhead_pct`` (ISSUE 8
    acceptance: <1% of step time), with the live-capture step cost and
    the compiled-vs-analytic FLOPs gap riding in the note."""
    return _hostmesh_probe(
        "import json\n"
        "from ptype_tpu.health.profiling import"
        " measure_profile_overhead\n"
        "print(json.dumps(measure_profile_overhead()))\n",
        STORE_PROBE_TIMEOUT)


def _compiled_cost_hostmesh() -> tuple[dict | None, str]:
    """Compiled-vs-analytic FLOPs per token on the 125M config (XLA
    cost_analysis, layer scan unrolled) — fills
    ``compiled_flops_per_token`` and the ISSUE 8 acceptance gap
    (``mfu_compiled`` within 10% of analytic, gap reported either
    way)."""
    return _hostmesh_probe(
        "import json\n"
        "from ptype_tpu.health.profiling import measure_compiled_cost\n"
        "print(json.dumps(measure_compiled_cost("
        "preset='optimus-125m', batch=8, seq=128)))\n",
        STORE_PROBE_TIMEOUT)


def _health_hostmesh() -> tuple[dict | None, str]:
    """Store-DP step loop with the goodput ledger + sampler armed —
    fills ``goodput_pct`` / ``step_breakdown`` /
    ``sampler_overhead_pct`` (ISSUE 5 acceptance: sampler < 1% of
    step time)."""
    return _hostmesh_probe(
        "import json\n"
        "from ptype_tpu.health.bench import measure_health_overhead\n"
        "print(json.dumps(measure_health_overhead()))\n",
        STORE_PROBE_TIMEOUT)


def _lockcheck_hostmesh() -> tuple[dict | None, str]:
    """Lock-order-watchdog cost probe (ISSUE 14): the health plane's
    lock-heavy control path (registry mutate + sampler tick — every
    lock off the lockcheck seam) armed vs disarmed, plus the
    disarmed-seam residue at the primitive. Bars: <1% disarmed, <5%
    armed."""
    return _hostmesh_probe(
        "import json\n"
        "from ptype_tpu.health.bench import measure_lockcheck_overhead\n"
        "print(json.dumps(measure_lockcheck_overhead()))\n",
        PROBE_TIMEOUT)


def _jitwatch_hostmesh() -> tuple[dict | None, str]:
    """Recompile-watchdog cost probe (ISSUE 15): the hot-region
    transfer-guard entry priced on a bare-dispatch A/B and charged
    against an engine-shaped step with its one host sync per
    iteration. Bar: armed < 5%."""
    return _hostmesh_probe(
        "import json\n"
        "from ptype_tpu.health.bench import measure_jitwatch_overhead\n"
        "print(json.dumps(measure_jitwatch_overhead()))\n",
        PROBE_TIMEOUT)


def _patch_store_metric(rec: dict) -> None:
    """Fill the Store metrics from the host-mesh probes — but ONLY when
    the worker left the fields null (the 1-chip case). A multi-chip run
    whose real ICI measurement FAILED leaves a note; overwriting it
    would hide the failure behind a mislabeled number. The two probes
    are independent subprocesses: a push-tree probe failure cannot null
    the allreduce metric."""
    if rec.get("value") is None:
        return
    if (rec.get("store_allreduce_gbps") is None
            and rec.get("store_allreduce_note") is None):
        gbps, note = _store_gbps_hostmesh()
        rec["store_allreduce_gbps"] = gbps
        rec["store_allreduce_note"] = note
    if (rec.get("store_push_tree_ms") is None
            and rec.get("store_push_tree_note") is None):
        probe, note = _push_tree_hostmesh()
        rec["store_push_tree_ms"] = (
            probe["bucketed_ms"] if probe else None)
        rec["store_push_tree_note"] = (
            f"per-leaf {probe['per_leaf_ms']} ms "
            f"({probe['speedup']}x), {probe['n_buckets']} buckets "
            f"/ {probe['n_leaves']} leaves, tiny preset; {note}"
            if probe else note)
    if rec.get("trace_overhead_pct") is None:
        # Always measured on the host mesh (the step loop the ISSUE 4
        # acceptance names), whatever platform earned the headline.
        probe, note = _trace_overhead_hostmesh()
        rec["trace_overhead_pct"] = (
            probe["trace_overhead_pct"] if probe else None)
        rec["trace_overhead_note"] = (
            f"disabled-hook {probe['trace_disabled_overhead_pct']}% "
            f"({probe['spans_per_step']} spans/step, traced "
            f"{probe['traced_step_ms']} ms vs untraced "
            f"{probe['untraced_step_ms']} ms); {note}"
            if probe else note)
    if rec.get("store_wire_gbps") is None:
        # Quantized-wire sweep: the block-scaled int8 allreduce vs
        # fp32 and PR 1's per-chunk int8 (ISSUE 6).
        probe, note = _wire_hostmesh()
        if probe:
            rec["store_wire_gbps"] = {
                "fp32": probe["fp32_gbps"],
                "int8_chunk": probe["int8_chunk_gbps"],
                "int8_block": probe["int8_block_gbps"]}
            sweep = " / ".join(
                f"{pct}%@{blk}" for blk, pct in
                probe["int8_block_wire_pct"].items())
            rec["store_wire_note"] = (
                f"int8 wire bytes {probe['int8_chunk_wire_pct']}% of "
                f"fp32 per-chunk, block-scaled {sweep}; "
                f"{probe['payload_mb']} MiB payload; {note}")
        else:
            rec["store_wire_note"] = note
    if rec.get("collective_overlap_pct") is None:
        # Fine-grained backward/collective overlap: the goodput
        # ledger's collective share, drain baseline vs overlap=True.
        probe, note = _overlap_hostmesh()
        rec["collective_overlap_pct"] = (
            probe["collective_overlap_pct"] if probe else None)
        rec["collective_note"] = (
            f"collective share "
            f"{probe['collective_share_drain_pct']}% drained → "
            f"{probe['collective_share_overlap_pct']}% overlapped "
            f"(step {probe['drain_step_ms']} → "
            f"{probe['overlap_step_ms']} ms); {note}"
            if probe else note)
    if rec.get("zero_opt_mem_mb") is None:
        # Sharded optimizer update (ZeRO-1): per-replica moment bytes
        # + step time vs the replicated store-DP baseline (ISSUE 7).
        probe, note = _zero_hostmesh()
        rec["zero_opt_mem_mb"] = (
            probe["zero_opt_mem_mb"] if probe else None)
        rec["zero_step_ms"] = probe["zero_step_ms"] if probe else None
        rec["zero_note"] = (
            f"replicated {probe['repl_opt_mem_mb']} MB → sharded "
            f"{probe['zero_opt_mem_mb']} MB per replica "
            f"({probe['opt_mem_ratio']}x, {probe['n_replicas']} "
            f"replicas); step {probe['repl_step_ms']} → "
            f"{probe['zero_step_ms']} ms; loss "
            f"{probe['final_loss_repl']} vs {probe['final_loss_zero']}"
            f"; {note}"
            if probe else note)
    if rec.get("zero2_grad_mem_mb") is None:
        # The rest of the ladder (ISSUE 17): ZeRO-2 scattered grads and
        # ZeRO-3 resident param shards, per replica.
        probe, note = _zero_ladder_hostmesh()
        rec["zero2_grad_mem_mb"] = (
            probe["zero2_grad_mem_mb"] if probe else None)
        rec["zero3_param_mem_mb"] = (
            probe["zero3_param_mem_mb"] if probe else None)
        rec["zero_ladder_note"] = (
            f"grads {probe['repl_grad_mem_mb']} → "
            f"{probe['zero2_grad_mem_mb']} MB (zero-2), params "
            f"{probe['repl_param_mem_mb']} → "
            f"{probe['zero3_param_mem_mb']} MB (zero-3) per replica, "
            f"{probe['n_replicas']} replicas, loss identical across "
            f"rungs; {note}"
            if probe else note)
    if rec.get("reshard_resume_steps") is None:
        # Live mid-run reshard vs the checkpoint-restore round trip
        # it replaces (ISSUE 17).
        probe, note = _reshard_hostmesh()
        rec["reshard_resume_steps"] = (
            probe["reshard_resume_steps"] if probe else None)
        rec["reshard_note"] = (
            f"8→4 live reshard {probe['reshard_ms']} ms, training "
            f"again in {probe['live_resume_ms']} ms "
            f"({probe['reshard_resume_steps']} steps) vs checkpoint "
            f"restore {probe['ckpt_resume_ms']} ms "
            f"({probe['ckpt_resume_steps']} steps) — "
            f"{probe['resume_speedup']}x; {note}"
            if probe else note)
    if rec.get("profile_overhead_pct") is None:
        # Profiling plane idle cost on the same host-mesh loop, plus
        # what a live capture costs (allowed to be visible) — ISSUE 8.
        probe, note = _profile_hostmesh()
        rec["profile_overhead_pct"] = (
            probe["profile_overhead_pct"] if probe else None)
        rec["profile_note"] = (
            f"ledger close {probe['ledger_close_us']}us/step, bare "
            f"{probe['bare_step_ms']} vs armed "
            f"{probe['armed_step_ms']} ms, live capture "
            f"{probe['capture_step_ms']} ms/step "
            f"({probe['capture_artifact_files']} artifacts); tiny "
            f"mfu gap {probe['mfu_gap_pct']}%; {note}"
            if probe else note)
    if rec.get("compiled_flops_per_token") is None:
        # XLA-compiled FLOPs vs the analytic MFU denominator on the
        # 125M config (gap reported, not hidden) — ISSUE 8.
        probe, note = _compiled_cost_hostmesh()
        rec["compiled_flops_per_token"] = (
            probe["compiled_flops_per_token"] if probe else None)
        rec["compiled_flops_note"] = (
            f"analytic {probe['analytic_flops_per_token']}, gap "
            f"{probe['mfu_gap_pct']}% ({probe['preset']} b="
            f"{probe['batch']} s={probe['seq']}, compile "
            f"{probe['compile_s']}s); {note}"
            if probe else note)
    if rec.get("goodput_pct") is None:
        # Health plane on the same host-mesh loop: live goodput +
        # breakdown, and the sampler cost alongside trace_overhead_pct
        # (ISSUE 5 acceptance: sampler < 1% of step time).
        probe, note = _health_hostmesh()
        rec["goodput_pct"] = probe["goodput_pct"] if probe else None
        rec["step_breakdown"] = (
            probe["step_breakdown"] if probe else None)
        rec["sampler_overhead_pct"] = (
            probe["sampler_overhead_pct"] if probe else None)
        rec["health_note"] = (
            f"sampler tick {probe['sampler_tick_us']}us at "
            f"{probe['sampler_cadence_s']}s cadence, ledger observer "
            f"{probe['ledger_observe_us']}us "
            f"({probe['ledger_overhead_pct']}% of step); {note}"
            if probe else note)
    if rec.get("lockcheck_overhead_pct") is None:
        # Lock-order watchdog cost on the control-plane probe
        # (ISSUE 14 acceptance: <1% disarmed, <5% armed).
        probe, note = _lockcheck_hostmesh()
        rec["lockcheck_overhead_pct"] = (
            probe["lockcheck_overhead_pct"] if probe else None)
        rec["lockcheck_note"] = (
            f"armed tick {probe['lockcheck_tick_us']}us -> "
            f"{probe['lockcheck_tick_armed_us']}us at "
            f"{probe['lockcheck_cadence_s']}s cadence "
            f"({probe['lockcheck_acquires_per_tick']} acquires/tick, "
            f"{probe['lockcheck_wrap_us_per_acquire']}us/acquire "
            f"wrapped); disarmed residue "
            f"{probe['lockcheck_disabled_overhead_pct']}% (plain "
            f"Lock by construction); "
            f"{probe['lockcheck_cycles']} cycles; {note}"
            if probe else note)
    if rec.get("jitwatch_overhead_pct") is None:
        # Recompile-watchdog cost (ISSUE 15 acceptance: armed < 5%).
        probe, note = _jitwatch_hostmesh()
        rec["jitwatch_overhead_pct"] = (
            probe["jitwatch_overhead_pct"] if probe else None)
        rec["jitwatch_note"] = (
            f"hot-region entry {probe['jitwatch_region_us']}us on a "
            f"{probe['jitwatch_step_ms']}ms engine-shaped step "
            f"(bare dispatch {probe['jitwatch_dispatch_us']}us); "
            f"{probe['jitwatch_steady_recompiles']} steady-state "
            f"recompiles; {note}"
            if probe else note)


def _finalize(line: str) -> None:
    """Emit the record line, patching in the host-mesh store metric
    when the worker left it null (single-chip sessions)."""
    rec = json.loads(line)
    _patch_store_metric(rec)
    _emit(rec)


def _cpu_fallback(errs: list[str]) -> bool:
    """Labeled CPU smoke number. Returns True when a line was emitted."""
    line, err, _ = _attempt({"JAX_PLATFORMS": "cpu"}, timeout=CPU_TIMEOUT)
    if line is not None:
        rec = json.loads(line)
        rec["fallback"] = "cpu"
        rec["error"] = ("tpu unavailable: " + (errs[-1] if errs else "?"))
        _patch_store_metric(rec)
        _emit(rec)
        return True
    errs.append(f"cpu fallback: {err}")
    return False


# ----------------------------------------------------- collectives bench


def collectives_main() -> None:
    """``make collectives-bench``: the ISSUE 6 data-plane probes on
    the host mesh, in-process (the Make target pins CPU + 8 virtual
    devices). Emits one labeled JSON line per probe and a combined
    tail record: the per-wire bucketed-allreduce bandwidth sweep
    (fp32 / per-chunk int8 / block-scaled int8), the quantized+EF
    push_tree timing, and the collective-share-of-step-time
    comparison with fine-grained overlap on."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ptype_tpu.parallel.collectives import (WireConfig,
                                                measure_wire_gbps)
    from ptype_tpu.parallel.mesh import build_mesh
    from ptype_tpu.parallel.tensorstore import measure_push_tree
    from ptype_tpu.train.store_dp import measure_overlap

    import jax

    n = len(jax.devices())
    mesh = build_mesh({"data": n})
    wires = measure_wire_gbps(mesh, mbytes=16, iters=3)
    _emit({"probe": "wire_gbps", **wires})
    push = measure_push_tree(
        mesh, preset="tiny", iters=2,
        wire=WireConfig(compress="int8", int8_min_bytes=0))
    _emit({"probe": "push_tree_int8_block", **push})
    overlap = measure_overlap(mesh, steps=6)
    _emit({"probe": "overlap", **overlap})
    _emit({
        "metric": "store collectives: block-scaled int8 wire + "
                  f"overlap ({n}-device host mesh)",
        "value": overlap["collective_overlap_pct"],
        "unit": "% of collective share hidden by overlap",
        "store_wire_gbps": {
            "fp32": wires["fp32_gbps"],
            "int8_chunk": wires["int8_chunk_gbps"],
            "int8_block": wires["int8_block_gbps"]},
        "store_push_tree_ms": push["bucketed_ms"],
        "collective_overlap_pct": overlap["collective_overlap_pct"],
        "collective_share_drain_pct":
            overlap["collective_share_drain_pct"],
        "collective_share_overlap_pct":
            overlap["collective_share_overlap_pct"],
    })


# ------------------------------------------------------------- hier bench


def hier_main() -> None:
    """``make hier-bench``: the ISSUE 18 hierarchical-collectives
    numbers on the emulated asymmetric host mesh, in-process. Emits
    one labeled JSON line per (outer, inner) factorization and a
    combined tail record: hierarchical vs flat bucketed-allreduce
    step time at exact-wire parity, the measured slow-leg wire bytes
    (the acceptance: <= 1/n_inner of the flat outer footprint), and
    the per-leg bandwidth model pricing both programs on the emulated
    ICI/DCN asymmetry."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ptype_tpu.parallel.collectives import measure_hier_allreduce
    from ptype_tpu.parallel.topology import Topology, factorizations

    import jax

    n = len(jax.devices())
    probes = {}
    for no, ni in factorizations(n):
        if 1 in (no, ni):
            continue  # degenerate legs: nothing to decompose
        topo = Topology.emulated_host(no, ni)
        p = measure_hier_allreduce(topo, mbytes=16, iters=4)
        probes[f"{no}x{ni}"] = p
        _emit({"probe": f"hier_allreduce_{no}x{ni}", **p})
    if not probes:
        _emit({"metric": "hierarchical allreduce", "value": None,
               "unit": "% of flat outer-leg bytes on the slow leg",
               "error": f"{n} devices admit no non-degenerate "
                        "(outer, inner) factorization"})
        raise SystemExit(2)
    head = probes.get("2x4") or next(iter(probes.values()))
    _emit({
        "metric": "hierarchical allreduce: slow-leg wire bytes "
                  f"({n}-device emulated asymmetric host mesh)",
        "value": head["slow_leg_pct"],
        "unit": "% of flat outer-leg bytes on the slow leg",
        "mesh_geometry": head["geometry"],
        "hier_step_ms": head["hier_step_ms"],
        "flat_step_ms": head["flat_step_ms"],
        "hier_slow_leg_bytes": head["hier_slow_leg_bytes"],
        "flat_outer_bytes": head["flat_outer_bytes"],
        "model_hier_ms": head["model_hier_ms"],
        "model_flat_ms": head["model_flat_ms"],
        "model_speedup": head["model_speedup"],
        "slow_leg_within_bound": head["hier_slow_leg_bytes"] <= (
            head["flat_outer_bytes"]
            // head["geometry"]["n_inner"] + 1),
    })


# ------------------------------------------------------------- zero bench


def zero_main() -> None:
    """``make zero-bench``: the ISSUE 7 acceptance numbers on the host
    mesh, in-process. Emits one labeled JSON line per probe and a
    combined tail record: per-replica optimizer-state bytes and step
    time for the ZeRO-1 sharded update vs the replicated store-DP
    baseline (exact wire AND the int8+EF wire), with the goodput
    ledger's new ``optimizer_ms`` leg from a short instrumented run."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ptype_tpu.health.goodput import GoodputLedger
    from ptype_tpu.metrics import MetricsRegistry
    from ptype_tpu.parallel.mesh import build_mesh
    from ptype_tpu.parallel.tensorstore import TensorStore
    from ptype_tpu.train.data import synthetic_batches
    from ptype_tpu.train.store_dp import StoreDPTrainer, measure_zero

    import jax
    from ptype_tpu.models import transformer as tfm

    n = len(jax.devices())
    mesh = build_mesh({"data": n})
    exact = measure_zero(mesh, steps=6)
    _emit({"probe": "zero_exact", **exact})
    int8 = measure_zero(mesh, steps=6, compress="int8")
    _emit({"probe": "zero_int8_ef", **int8})

    # The optimizer leg of the goodput breakdown under zero=True.
    cfg = tfm.preset("tiny")
    trainer = StoreDPTrainer(cfg, TensorStore(mesh),
                             rng=jax.random.PRNGKey(0), zero=True)
    stream = synthetic_batches(cfg.vocab_size, 16, 128, seed=9)
    trainer.step(next(stream))  # compile + warm outside the ledger
    ledger = GoodputLedger(registry=MetricsRegistry()).install()
    try:
        for _ in range(6):
            trainer.step(next(stream))
    finally:
        ledger.uninstall()
    breakdown = ledger.summary()["step_breakdown"]
    _emit({"probe": "zero_breakdown", "step_breakdown": breakdown})

    # The full ladder + the live-reshard-vs-checkpoint race (ISSUE 17).
    from ptype_tpu.train.store_dp import (measure_reshard,
                                          measure_zero_ladder)

    ladder = measure_zero_ladder(mesh, steps=4)
    _emit({"probe": "zero_ladder", **ladder})
    reshard = measure_reshard(steps=3)
    _emit({"probe": "zero_reshard", **reshard})
    print(f"\n  ZeRO ladder ({n}-device host mesh, per replica):")
    print(f"  {'mode':<7}{'opt MB':>9}{'grad MB':>9}"
          f"{'param MB':>10}{'step ms':>9}{'loss':>10}")
    for name, r in ladder["ladder"].items():
        print(f"  {name:<7}{r['opt_mem_mb']:>9}{r['grad_mem_mb']:>9}"
              f"{r['param_mem_mb']:>10}{r['step_ms']:>9}"
              f"{r['final_loss']:>10}")
    print(f"  live reshard 8→4: {reshard['reshard_ms']} ms, training "
          f"again in {reshard['reshard_resume_steps']} steps vs "
          f"{reshard['ckpt_resume_steps']} steps via checkpoint "
          f"restore ({reshard['resume_speedup']}x)\n")

    _emit({
        "metric": "zero-1 sharded optimizer update "
                  f"({n}-device host mesh)",
        "value": exact["opt_mem_ratio"],
        "unit": "x less optimizer memory per replica",
        "zero_opt_mem_mb": exact["zero_opt_mem_mb"],
        "repl_opt_mem_mb": exact["repl_opt_mem_mb"],
        "zero_step_ms": exact["zero_step_ms"],
        "repl_step_ms": exact["repl_step_ms"],
        "zero_int8_step_ms": int8["zero_step_ms"],
        "optimizer_ms": breakdown.get("optimizer_ms"),
        "final_loss_zero": exact["final_loss_zero"],
        "final_loss_repl": exact["final_loss_repl"],
        "zero2_grad_mem_mb": ladder["zero2_grad_mem_mb"],
        "zero3_param_mem_mb": ladder["zero3_param_mem_mb"],
        "reshard_resume_steps": reshard["reshard_resume_steps"],
    })


# ---------------------------------------------------------- profile bench


def profile_main() -> None:
    """``make profile-bench``: the ISSUE 8 profiling-plane numbers on
    the host mesh, in-process. Emits one labeled JSON line per probe
    and a combined tail record: the capture-disabled overhead of the
    armed plane on the store-DP loop (acceptance <1%), the live
    capture cost, and the compiled-vs-analytic FLOPs gap on the 125M
    config (acceptance: within 10%, reported either way)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ptype_tpu.health.profiling import (measure_compiled_cost,
                                            measure_profile_overhead)

    overhead = measure_profile_overhead()
    _emit({"probe": "profile_overhead", **overhead})
    cost = measure_compiled_cost(preset="optimus-125m", batch=8,
                                 seq=128)
    _emit({"probe": "compiled_cost_125m", **cost})
    import jax

    _emit({
        "metric": "profiling plane: capture-disabled overhead "
                  f"({len(jax.devices())}-device host mesh)",
        "value": overhead["profile_overhead_pct"],
        "unit": "% of store-DP step time",
        "profile_overhead_pct": overhead["profile_overhead_pct"],
        "capture_step_ms": overhead["capture_step_ms"],
        "bare_step_ms": overhead["bare_step_ms"],
        "compiled_flops_per_token": cost["compiled_flops_per_token"],
        "analytic_flops_per_token": cost["analytic_flops_per_token"],
        "mfu_gap_pct": cost["mfu_gap_pct"],
        "mfu_gap_within_10pct": abs(cost["mfu_gap_pct"]) <= 10.0,
    })


def jitwatch_main() -> None:
    """``make jitwatch-bench``: the ISSUE 15 dispatch-discipline
    numbers in-process — the armed watchdog's per-step price (hot
    region entry charged against an engine-shaped step, <5% bar) and
    a zero-steady-state-recompiles check on the probe itself."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ptype_tpu.health.bench import measure_jitwatch_overhead

    probe = measure_jitwatch_overhead()
    _emit({"probe": "jitwatch_overhead", **probe})
    _emit({
        "metric": "jitwatch: armed hot-region overhead",
        "value": probe["jitwatch_overhead_pct"],
        "unit": "% of engine-shaped step time",
        "jitwatch_overhead_pct": probe["jitwatch_overhead_pct"],
        "jitwatch_region_us": probe["jitwatch_region_us"],
        "jitwatch_step_ms": probe["jitwatch_step_ms"],
        "jitwatch_steady_recompiles":
            probe["jitwatch_steady_recompiles"],
        "within_5pct_bar": probe["jitwatch_overhead_pct"] < 5.0,
    })


def forensics_main() -> None:
    """``bench.py --forensics``: the ISSUE 20 tail-forensics numbers —
    the marginal cost of the always-on exemplar slots on a histogram
    observe, and the full armed per-request seam (``answered`` with a
    five-stage split, trace id racing the exemplar reservoirs) priced
    against a 20 ms reference request (the traffic bench's fake
    replica), <=1% bar. Tight loops over the real calls, never a
    wall-clock A/B — the signal is microseconds against a
    multi-millisecond request."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ptype_tpu import metrics as metrics_mod
    from ptype_tpu.gateway.slo import SLOTracker
    from ptype_tpu.health.forensics import measure_forensics_overhead

    probe = measure_forensics_overhead()
    _emit({"probe": "forensics_exemplar", **probe})
    reg = metrics_mod.MetricsRegistry()
    slo = SLOTracker("llm", registry=reg, slo_ttft_p99_ms=10_000.0)
    stages = {"queue-wait": 1.0, "route": 0.2, "prefill": 12.0,
              "migrate": 3.0, "decode": 8.0}
    iters = 5000
    t0 = time.perf_counter()
    for _ in range(iters):
        slo.answered(25.0, tokens=8, ttft_ms=20.0, tpot_ms=1.0,
                     stages=stages, trace_id="bench-forensics-trace")
    per_req_us = (time.perf_counter() - t0) / iters * 1e6
    ref_request_ms = 20.0
    pct = per_req_us / (ref_request_ms * 1e3) * 100.0
    _emit({
        "metric": "tail forensics: armed per-request seam cost",
        "value": round(pct, 4),
        "unit": f"% of a {ref_request_ms:.0f}ms request",
        "forensics_request_seam_us": round(per_req_us, 2),
        "forensics_exemplar_marginal_us": round(
            probe["exemplar_marginal_us"], 3),
        "forensics_observe_plain_us": round(
            probe["observe_plain_us"], 3),
        "forensics_observe_armed_us": round(
            probe["observe_armed_us"], 3),
        "forensics_overhead_pct": round(pct, 4),
        "within_1pct_bar": pct < 1.0,
        "notes": {
            "forensics_request_seam_us":
                "one answered() with latency + 5 stage histograms, "
                "exemplar reservoirs armed and full (steady-state "
                "replace-min), worst-TTFT/TPOT fold included",
        },
    })


# ------------------------------------------------------------ serve bench


def _serve_paged_probe() -> dict:
    """Paged-engine host probe (ISSUE 9 acceptance numbers): a
    shared-prefix workload routed through the gateway with
    ``prefix_affinity_key`` against the same workload with unique
    prefixes (every request cold). Returns the tail fields:

    - ``serve_prefix_hit_speedup``: cold-pass wall / shared-pass wall
      (>1.5x is the bar — the shared pass prefills one prefix once,
      then only divergent tails);
    - ``serve_kv_util_pct``: peak live-block pool utilization sampled
      across both passes;
    - ``serve_prefill_stall_ms``: the engines' max co-batched
      decode-step stall under chunked admission (bounded by the
      ``prefill_chunk`` budget, vs the whole-prompt prefill today).

    Serving-ledger fields (ISSUE 10), from the same driven traffic:

    - ``serve_ttft_p99_ms`` / ``serve_tpot_ms``: the ledgers'
      time-to-first-token p99 and median inter-token time across both
      replicas — the histograms `obs serve` and the ``ttft-p99`` rule
      read, here measured on real gateway-routed requests;
    - ``serving_ledger_overhead_pct``: ledger seam cost per engine
      iteration (``measure_seam_cost_us``, a tight loop over the real
      seam calls — measured like PR 8's ``profile_overhead_pct``,
      because wall-clock A/B on a shared host reports scheduler
      jitter) divided by the measured mean engine-iteration time.
      The bar is <1%; the number is REPORTED here, never asserted.
    """
    import threading

    import jax.numpy as jnp
    import numpy as np

    from ptype_tpu.actor import ActorServer
    from ptype_tpu.coord.core import CoordState
    from ptype_tpu.coord.local import LocalCoord
    from ptype_tpu.gateway import GatewayConfig, InferenceGateway
    from ptype_tpu.models import transformer as tfm
    from ptype_tpu.registry import CoordRegistry
    from ptype_tpu.serve_engine import (PagedGeneratorActor,
                                        prefix_affinity_key)

    PREFIX, TAIL, MAX_NEW, N_REQ, CHUNK, BT = 224, 4, 4, 7, 32, 16
    N_THREADS = 2
    # Big enough that prefill COMPUTE dominates dispatch on CPU — the
    # tiny preset is dispatch-bound and a 160-token prefill costs the
    # same as a 4-token one there.
    cfg = tfm.preset("tiny", d_model=256, n_layers=4, d_ff=512,
                     max_seq=256, dtype=jnp.float32)
    rng = np.random.default_rng(11)

    def mk(prefix, tail_len):
        tail = rng.integers(1, cfg.vocab_size, tail_len)
        return jnp.asarray(
            np.concatenate([prefix, tail]).astype(np.int32))[None]

    base = PagedGeneratorActor(cfg, n_slots=4, block_tokens=BT,
                               prefill_chunk=CHUNK)
    twin = PagedGeneratorActor(cfg, params=base.params, n_slots=4,
                               block_tokens=BT, prefill_chunk=CHUNK)
    actors = [base, twin]
    state = CoordState(sweep_interval=0.1)
    coord = LocalCoord(state)
    registry = CoordRegistry(coord, lease_ttl=2.0)
    servers, regs = [], []
    for i, a in enumerate(actors):
        s = ActorServer("127.0.0.1", 0)
        s.register(a, "Generator")
        s.serve()
        servers.append(s)
        regs.append(registry.register("llm-paged", f"r{i}",
                                      "127.0.0.1", s.port))
    gw = None
    util_max = [0.0]
    stop = threading.Event()

    def poll_util():
        while not stop.is_set():
            for a in actors:
                util_max[0] = max(util_max[0],
                                  a.pool.stats()["kv_util_pct"])
            time.sleep(0.002)

    def one(p):
        key = prefix_affinity_key(np.asarray(p[0]), BT)
        np.asarray(gw.generate(p, MAX_NEW, affinity_key=key))

    def drive(prompts):
        import queue

        q = queue.Queue()
        for p in prompts[1:]:
            q.put(p)
        errs = []

        def worker():
            while True:
                try:
                    p = q.get_nowait()
                except queue.Empty:
                    return
                try:
                    one(p)
                except Exception as e:  # noqa: BLE001
                    # A lost request silently SHRINKS the measured
                    # wall; fail the probe loudly instead.
                    errs.append(e)
                    return

        threads = [threading.Thread(target=worker)
                   for _ in range(N_THREADS)]
        t0 = time.perf_counter()
        # Head request runs ALONE (in the shared pass it is the one
        # cold prefill that seals the prefix); the rest concurrently —
        # the same discipline for both passes.
        one(prompts[0])
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        if errs:
            raise errs[0]
        return time.perf_counter() - t0

    try:
        # Warm every compile bucket on BOTH replicas off the clock
        # (unique warm prefix: its cached blocks can't be hit later).
        warm = mk(rng.integers(1, cfg.vocab_size, PREFIX), TAIL)
        for a in actors:
            np.asarray(a.Generate(warm, MAX_NEW))
            # The warmup's compiles land on the stall meter; the
            # measured passes start it clean.
            a._max_stall_ms = a._last_stall_ms = 0.0
        gw = InferenceGateway(
            registry, "llm-paged",
            GatewayConfig(probe_interval_s=0.2, probe_timeout_s=2.0,
                          default_deadline_s=120.0,
                          max_queue_depth=64))
        deadline = time.monotonic() + 10
        while gw.pool.n_healthy() < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        poller = threading.Thread(target=poll_util, daemon=True)
        poller.start()
        # Pass A: every request a UNIQUE prefix — all prefills cold.
        cold_s = drive([mk(rng.integers(1, cfg.vocab_size, PREFIX),
                           TAIL) for _ in range(N_REQ)])
        # Pass B: ONE shared prefix, distinct tails — affinity routing
        # lands the stream on one replica, whose prefix cache hits for
        # every full block after the first request.
        shared = rng.integers(1, cfg.vocab_size, PREFIX)
        warm_s = drive([mk(shared, TAIL) for _ in range(N_REQ)])
        stop.set()
        poller.join(timeout=5)
        infos = [a.Info() for a in actors]
        hits = [i["prefix_hits"] for i in infos]
        # Serving-ledger tail (ISSUE 10): TTFT/TPOT from the ledgers
        # that metered the driven traffic; overhead = seam cost per
        # iteration / measured iteration time.
        from ptype_tpu.health.serving import measure_seam_cost_us

        ttft_p99 = max(i.get("ttft_p99_ms", 0.0) for i in infos)
        tpot_ms = max(i.get("tpot_p50_ms", 0.0) for i in infos)
        step_means = [a.ledger.iteration_summary()["step_ms_mean"]
                      for a in actors]
        step_ms = max([m for m in step_means if m > 0] or [0.0])
        seam_us = measure_seam_cost_us()["seam_cost_us"]
        overhead_pct = (round(100.0 * seam_us / (step_ms * 1e3), 4)
                        if step_ms > 0 else None)
        return {
            "serve_prefix_hit_speedup": round(cold_s / warm_s, 2),
            "serve_kv_util_pct": util_max[0],
            "serve_prefill_stall_ms":
                max(i["prefill_stall_ms"] for i in infos),
            "serve_prefix_hits": max(hits),
            "serve_prefix_hit_rate":
                max(i["prefix_hit_rate"] for i in infos),
            "serve_kv_evictions":
                sum(i["kv_evictions"] for i in infos),
            "serve_prefill_chunk_tokens": CHUNK,
            "serve_block_tokens": BT,
            "serve_ttft_p99_ms": ttft_p99,
            "serve_tpot_ms": tpot_ms,
            "serving_ledger_overhead_pct": overhead_pct,
            "serving_ledger_seam_cost_us": seam_us,
            "serve_step_ms_mean": step_ms,
            "paged_cold_wall_s": round(cold_s, 3),
            "paged_shared_wall_s": round(warm_s, 3),
            "notes": (
                f"paged probe: {N_REQ} reqs x ({PREFIX} prefix + "
                f"{TAIL} tail) tokens, {N_THREADS} threads, 2 paged "
                f"replicas (d_model=256/L4), affinity-routed; "
                f"speedup = unique-prefix wall / shared-prefix wall; "
                f"stall is the max co-batched decode-step wait under "
                f"{CHUNK}-token chunked admission; ttft/tpot from the "
                f"serving ledgers on the same traffic; ledger overhead "
                f"= seam cost per iteration / mean engine-iteration "
                f"wall (<1% bar, reported not asserted)"),
        }
    finally:
        stop.set()
        if gw is not None:
            gw.close()
        for r in regs:
            r.close()
        for s in servers:
            s.close()
        for a in actors:
            a.close()
        state.close()


def _serve_spec_probe() -> dict:
    """Speculative-decoding batch-1 probe (ISSUE 12 acceptance
    numbers): single-stream decode tokens/sec through the paged engine
    with speculation armed vs the plain engine, at bit-identical
    greedy output. Latency is the frontier batching can't touch — a
    lone stream pays one full target forward per token; speculation
    pays one draft scan + ONE batched verify per k+1 tokens.

    Tail fields: ``serve_batch1_tokens_per_sec`` (spec) /
    ``serve_batch1_tokens_per_sec_nonspec`` / ``serve_spec_speedup``
    (≥1.5x is the bar, reported not asserted) /
    ``serve_spec_accept_rate`` / ``serve_spec_greedy_identical``.

    Honesty note (the CPU-mesh GB/s discipline): the draft is the
    layer-truncated variant of the target
    (``generate.truncated_draft_params`` — half the layers, zero
    extra parameter memory), which on a RANDOM-INIT target agrees
    with the full model nearly always (residual blocks barely
    perturb the embed→head logits), so the measured accept rate
    sits at its ceiling and the probe measures the ENGINE's window
    mechanics: dispatch/sync amortization over k+1-token windows on
    the dispatch-bound tiny preset, standing in for the weight-read
    amortization on memory-bound hardware. Trained drafts land
    lower; adaptive k is what keeps a collapsed one from taxing
    every token (its backoff has its own tier-1 coverage).
    """
    import jax.numpy as jnp
    import numpy as np

    from ptype_tpu.models import generate as gen_mod
    from ptype_tpu.models import transformer as tfm
    from ptype_tpu.serve_engine import PagedGeneratorActor, SpecConfig

    MAX_NEW, REPS, K, PLEN = 64, 6, 6, 8
    cfg = tfm.preset("tiny", dtype=jnp.float32)
    rng = np.random.default_rng(5)

    def mk():
        return jnp.asarray(
            rng.integers(1, cfg.vocab_size, PLEN).astype(np.int32)
        )[None]

    base = PagedGeneratorActor(cfg, n_slots=2, block_tokens=16)
    dparams, dcfg = gen_mod.truncated_draft_params(
        base.params, cfg, n_layers=max(1, cfg.n_layers // 2))
    spec = SpecConfig(draft_params=dparams, draft_cfg=dcfg, k=K,
                      adaptive=False)
    sp = PagedGeneratorActor(cfg, params=base.params, n_slots=2,
                             block_tokens=16, spec=spec)
    try:
        # 8-token prompts never fill a block: no prefix reuse, so the
        # SAME prompts drive both sides (and tail windows with every
        # k_eff < K compile during warmup, off the clock).
        prompts = [mk() for _ in range(REPS)]
        warm = mk()
        np.asarray(base.Generate(warm, MAX_NEW))
        np.asarray(sp.Generate(warm, MAX_NEW))

        def drive(actor):
            t0 = time.perf_counter()
            outs = [np.asarray(actor.Generate(p, MAX_NEW))
                    for p in prompts]
            return time.perf_counter() - t0, outs

        wall_ns, outs_ns = drive(base)
        wall_sp, outs_sp = drive(sp)
        identical = all(np.array_equal(a, b)
                        for a, b in zip(outs_ns, outs_sp))
        info = sp.Info()
        tps_sp = REPS * MAX_NEW / wall_sp
        tps_ns = REPS * MAX_NEW / wall_ns
        return {
            "serve_batch1_tokens_per_sec": round(tps_sp, 1),
            "serve_batch1_tokens_per_sec_nonspec": round(tps_ns, 1),
            "serve_spec_speedup": round(tps_sp / tps_ns, 2),
            "serve_spec_accept_rate": info.get("spec_accept_rate"),
            "serve_spec_k": K,
            "serve_spec_windows": info.get("spec_windows"),
            "serve_spec_greedy_identical": bool(identical),
            "spec_notes": (
                f"batch-1 probe: {REPS} reqs x {MAX_NEW} greedy "
                f"tokens, {PLEN}-token prompts, tiny preset, "
                f"layer-truncated draft ({dcfg.n_layers}/"
                f"{cfg.n_layers} layers) k={K} — accept rate sits "
                f"at its ceiling on a random-init target (see "
                f"docs/PERF.md honesty note); speedup = spec "
                f"tokens/sec over the plain paged engine at "
                f"bit-identical output"),
        }
    finally:
        sp.close()
        base.close()


def spec_main() -> None:
    """``make spec-bench``: the speculative-decoding probe alone."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    spec = _serve_spec_probe()
    _emit({"probe": "serve_spec_decode", **spec})
    _emit({
        "metric": "batch-1 speculative decode speedup "
                  "(cpu host, tiny preset, self-draft)",
        "value": spec["serve_spec_speedup"],
        "unit": "x tokens/sec vs plain paged engine",
        **spec,
    })


def _disagg_probe() -> dict:
    """Disaggregated-serving host probe (ISSUE 16 acceptance
    numbers): the SAME mixed load — a long-prompt TTFT stream under
    continuous short-prompt decode traffic — driven through (a) an
    interleaved fleet (two unified replicas, every engine co-batching
    chunked prefills with decode steps) and (b) a disaggregated fleet
    (one prefill-class + one decode-class replica, the gateway's
    two-stage router migrating KV blocks over the wire). Tail fields:

    - ``disagg_ttft_p99_ms`` vs ``interleaved_ttft_p99_ms``: p99
      client-observed time-to-first-token of the long-prompt stream
      (``max_new=1`` — the wall IS the TTFT), measured while the
      decode load runs. The bar: disagg beats interleaved, because
      the prefill replica never waits on a co-batched decode step;
    - ``disagg_greedy_identical``: gateway-routed disagg tokens are
      bit-equal to solo decode over the exact wire (the zero
      token-level-divergence acceptance check);
    - ``migrate_ms_per_block`` / ``migrate_dedup_ratio``: the q8
      wire's per-block transfer cost and the chain-hash manifest's
      dedup rate on a shared-prefix request family (first request
      ships every block, siblings ship only their tails).
    """
    import threading

    import jax.numpy as jnp
    import numpy as np

    from ptype_tpu.actor import ActorServer
    from ptype_tpu.coord.core import CoordState
    from ptype_tpu.coord.local import LocalCoord
    from ptype_tpu.gateway import GatewayConfig, InferenceGateway
    from ptype_tpu.models import transformer as tfm
    from ptype_tpu.registry import CoordRegistry
    from ptype_tpu.serve_engine import PagedGeneratorActor

    PREFIX, TAIL, BT, CHUNK = 224, 4, 16, 32
    N_TTFT, N_DECODE_THREADS, SHORT_MAX_NEW = 12, 2, 24
    # Big enough that prefill COMPUTE dominates dispatch on CPU (the
    # same sizing argument as the paged probe above).
    cfg = tfm.preset("tiny", d_model=256, n_layers=4, d_ff=512,
                     max_seq=256, dtype=jnp.float32)
    rng = np.random.default_rng(16)
    params_box = [None]

    def mk(n):
        return jnp.asarray(
            rng.integers(1, cfg.vocab_size, n).astype(np.int32))[None]

    def mk_tailed(shared):
        tail = rng.integers(1, cfg.vocab_size, TAIL)
        return jnp.asarray(
            np.concatenate([shared, tail]).astype(np.int32))[None]

    def mig_segment(pre, dec):
        """Direct protocol drive on a shared-prefix family: q8 wire
        cost per shipped block + the manifest's dedup ratio."""
        shared = rng.integers(1, cfg.vocab_size, PREFIX)
        need_tot = res_tot = shipped = 0
        ship_ms = 0.0
        for _ in range(4):
            p = mk_tailed(shared)
            rep = pre.Prefill(p, 8)
            plan = dec.MigratePlan(p, 8)
            need_tot += len(plan["need"])
            res_tot += int(plan["resident"])
            t0 = time.perf_counter()
            wire = pre.ExportBlocks(rep["export_id"], plan["need"],
                                    "q8")
            dec.ImportBlocks(plan["ticket"], wire)
            ship_ms += (time.perf_counter() - t0) * 1e3
            shipped += len(plan["need"]) + 1  # tail always ships
            pre.ReleaseExport(rep["export_id"])
            dec.MigrateDecode(plan["ticket"], rep["first_token"])
        return {
            "migrate_ms_per_block": round(ship_ms / shipped, 3),
            "migrate_dedup_ratio":
                round(res_tot / (need_tot + res_tot), 3),
        }

    def run_pass(classes, disagg):
        state = CoordState(sweep_interval=0.1)
        registry = CoordRegistry(LocalCoord(state), lease_ttl=2.0)
        engines, servers, regs = [], [], []
        for i, scls in enumerate(classes):
            a = PagedGeneratorActor(
                cfg, params=params_box[0], n_slots=4,
                block_tokens=BT, prefill_chunk=CHUNK,
                serve_class=scls)
            if params_box[0] is None:
                params_box[0] = a.params
            s = ActorServer("127.0.0.1", 0)
            s.register(a, "Generator")
            s.serve()
            regs.append(registry.register("llm-disagg", f"r{i}",
                                          "127.0.0.1", s.port))
            engines.append(a)
            servers.append(s)
        gw = None
        stop = threading.Event()
        errs = []
        try:
            # Warm every compile bucket OFF the clock: prefill
            # chunks, decode steps, and (disagg) the pack/unpack
            # programs via one direct migration.
            for a in engines:
                np.asarray(a.Generate(mk(PREFIX + TAIL), 1))
                np.asarray(a.Generate(mk(8), SHORT_MAX_NEW))
            if disagg:
                pre, dec = engines
                rep = pre.Prefill(mk(PREFIX + TAIL), 8)
                plan = dec.MigratePlan(mk(PREFIX + TAIL), 8)
                wire = pre.ExportBlocks(rep["export_id"],
                                        plan["need"], "q8")
                dec.ImportBlocks(plan["ticket"], wire)
                pre.ReleaseExport(rep["export_id"])
                dec.MigrateDecode(plan["ticket"], rep["first_token"])
            gw = InferenceGateway(
                registry, "llm-disagg",
                GatewayConfig(probe_interval_s=0.2,
                              probe_timeout_s=2.0,
                              default_deadline_s=120.0,
                              max_queue_depth=64, disagg=disagg,
                              kv_wire="exact"))
            want = set(classes)
            deadline = time.monotonic() + 10
            while (time.monotonic() < deadline
                   and not want <= {r.serve_class()
                                    for r in gw.pool.healthy()}):
                time.sleep(0.05)

            def decode_load():
                p = mk(8)
                while not stop.is_set():
                    try:
                        np.asarray(gw.generate(p, SHORT_MAX_NEW))
                    except Exception as e:  # noqa: BLE001
                        if not stop.is_set():
                            errs.append(e)
                        return

            threads = [threading.Thread(target=decode_load,
                                        daemon=True)
                       for _ in range(N_DECODE_THREADS)]
            for t in threads:
                t.start()
            time.sleep(0.3)  # decode streams reach steady state
            walls = []
            for _ in range(N_TTFT):
                p = mk(PREFIX + TAIL)  # unique: every prefill cold
                t0 = time.perf_counter()
                np.asarray(gw.generate(p, 1))
                walls.append((time.perf_counter() - t0) * 1e3)
            stop.set()
            for t in threads:
                t.join(timeout=60)
            if errs:
                raise errs[0]
            extra = {}
            if disagg:
                pre, dec = engines
                pv = mk(PREFIX + TAIL)
                ref = np.asarray(pre.Generate(pv, 8))
                out = np.asarray(gw.generate(pv, 8))
                extra["greedy_identical"] = bool((out == ref).all())
                extra.update(mig_segment(pre, dec))
            return {"ttft_ms": walls, **extra}
        finally:
            stop.set()
            if gw is not None:
                gw.close()
            for r in regs:
                r.close()
            for s in servers:
                s.close()
            for a in engines:
                a.close()
            state.close()

    inter = run_pass(("unified", "unified"), disagg=False)
    dis = run_pass(("prefill", "decode"), disagg=True)
    i99 = float(np.percentile(inter["ttft_ms"], 99))
    d99 = float(np.percentile(dis["ttft_ms"], 99))
    return {
        "disagg_ttft_p99_ms": round(d99, 2),
        "interleaved_ttft_p99_ms": round(i99, 2),
        "disagg_ttft_p50_ms":
            round(float(np.percentile(dis["ttft_ms"], 50)), 2),
        "interleaved_ttft_p50_ms":
            round(float(np.percentile(inter["ttft_ms"], 50)), 2),
        "disagg_ttft_speedup":
            round(i99 / d99, 2) if d99 > 0 else None,
        "disagg_beats_interleaved": d99 < i99,
        "disagg_greedy_identical": dis["greedy_identical"],
        "migrate_ms_per_block": dis["migrate_ms_per_block"],
        "migrate_dedup_ratio": dis["migrate_dedup_ratio"],
        "migrate_wire": "q8",
        "notes": (
            f"disagg probe: {N_TTFT} cold {PREFIX}+{TAIL}-token "
            f"prefills (max_new=1, wall = TTFT) under "
            f"{N_DECODE_THREADS} continuous short-prompt decode "
            f"streams ({SHORT_MAX_NEW} tokens each), 2 replicas "
            f"(d_model=256/L4), {CHUNK}-token chunked admission; "
            f"interleaved = two unified replicas, disagg = "
            f"prefill+decode classes with KV migration; dedup/cost "
            f"segment: 4 shared-prefix requests over the q8 wire "
            f"(first ships every block, siblings only tails)"),
    }


def disagg_main() -> None:
    """``make disagg-bench``: the ISSUE 16 disaggregated-serving
    numbers — prefill-isolation TTFT vs the interleaved fleet, the
    q8 wire's per-block cost, and the manifest dedup ratio."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    rec = _disagg_probe()
    _emit({"probe": "serve_disagg", **rec})
    _emit({
        "metric": "disaggregated prefill TTFT p99 under decode load "
                  "(cpu host, 2 replicas)",
        "value": rec["disagg_ttft_p99_ms"],
        "unit": "ms vs interleaved fleet",
        **rec,
    })


def serve_main() -> None:
    """``make serve-bench``: tail latency THROUGH the inference
    gateway on the host (CPU, tiny preset), against the failure mode
    the gateway exists for — a fleet where one replica is slow.

    Three replicas serve one service; one of them delays every call by
    ``SLOW_MS``. The same request stream is driven (a) through the
    gateway (admission + least-loaded routing) and (b) through the raw
    round-robin balanced client. The tail record carries
    ``serve_p99_ms`` / ``serve_tokens_per_sec`` for the gateway path
    and the round-robin p99 for the comparison the acceptance bar
    names: least-loaded routing must keep the slow replica out of the
    gateway's tail, while round-robin serializes every third request
    behind it. A second probe (:func:`_serve_paged_probe`) adds the
    paged-engine tail fields: ``serve_prefix_hit_speedup`` /
    ``serve_kv_util_pct`` / ``serve_prefill_stall_ms``.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import threading

    import jax.numpy as jnp
    import numpy as np

    from ptype_tpu.actor import ActorServer
    from ptype_tpu.coord.core import CoordState
    from ptype_tpu.coord.local import LocalCoord
    from ptype_tpu.gateway import GatewayConfig, InferenceGateway
    from ptype_tpu.models import transformer as tfm
    from ptype_tpu.registry import CoordRegistry
    from ptype_tpu.rpc import Client, ConnConfig

    SLOW_MS = 250.0
    N_REQ = 48
    N_THREADS = 2
    MAX_NEW = 8

    class _SlowReplica:
        """Delegates to a real generator, SLOW_MS late — a dying disk,
        a thermally throttled chip, a noisy neighbor."""

        def __init__(self, inner):
            self._inner = inner

        def Generate(self, *a, **kw):
            time.sleep(SLOW_MS / 1000.0)
            return self._inner.Generate(*a, **kw)

        def Info(self):
            time.sleep(SLOW_MS / 1000.0)  # probes see the slowness too
            return self._inner.Info()

    from ptype_tpu.serve import GeneratorActor

    state = CoordState(sweep_interval=0.1)
    coord = LocalCoord(state)
    registry = CoordRegistry(coord, lease_ttl=2.0)
    cfg = tfm.preset("tiny", dtype=jnp.float32)
    base = GeneratorActor(cfg)
    actors = [GeneratorActor(cfg, params=base.params),
              GeneratorActor(cfg, params=base.params),
              _SlowReplica(GeneratorActor(cfg, params=base.params))]
    servers, regs = [], []
    prompt = jnp.ones((1, 8), jnp.int32)
    for i, a in enumerate(actors):
        s = ActorServer("127.0.0.1", 0)
        s.register(a, "Generator")
        s.serve()
        servers.append(s)
        regs.append(registry.register("llm-bench", f"r{i}", "127.0.0.1",
                                      s.port))
    gw = client = None
    try:
        base.Generate(prompt, MAX_NEW)  # compile once; params shared

        def drive(call, warm_ms=None):
            lat, lock = [], threading.Lock()
            idx = iter(range(N_REQ))

            def worker():
                while True:
                    with lock:
                        try:
                            next(idx)
                        except StopIteration:
                            return
                    t0 = time.perf_counter()
                    out = call()
                    np.asarray(out)  # force the async result
                    ms = (time.perf_counter() - t0) * 1000.0
                    with lock:
                        lat.append(ms)

            threads = [threading.Thread(target=worker)
                       for _ in range(N_THREADS)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            wall = time.perf_counter() - t0
            lat.sort()
            p = lambda q: lat[min(len(lat) - 1,  # noqa: E731
                                  int(round(q * (len(lat) - 1))))]
            return {"p50_ms": round(p(0.50), 1),
                    "p99_ms": round(p(0.99), 1),
                    "tokens_per_sec": round(N_REQ * MAX_NEW / wall, 1),
                    "wall_s": round(wall, 2)}

        gw = InferenceGateway(
            registry, "llm-bench",
            GatewayConfig(probe_interval_s=0.2, probe_timeout_s=2.0,
                          default_deadline_s=60.0, max_queue_depth=64))
        deadline = time.monotonic() + 10
        while gw.pool.n_healthy() < 3 and time.monotonic() < deadline:
            time.sleep(0.05)
        gw_stats = drive(lambda: gw.generate(prompt, MAX_NEW))

        client = Client("bench", "llm-bench", registry,
                        ConnConfig(max_connections=0, retries=0,
                                   call_timeout=60.0,
                                   initial_node_timeout=5.0))
        rr_stats = drive(
            lambda: client.call("Generator.Generate", prompt, MAX_NEW))

        paged = _serve_paged_probe()
        _emit({"probe": "serve_paged_engine", **paged})
        spec = _serve_spec_probe()
        _emit({"probe": "serve_spec_decode", **spec})
        _emit({
            "metric": "serve p99 through gateway vs round-robin "
                      "(cpu host, tiny preset, 1 of 3 replicas "
                      f"{int(SLOW_MS)}ms slow)",
            "value": gw_stats["p99_ms"],
            "unit": "ms",
            "serve_p99_ms": gw_stats["p99_ms"],
            "serve_p50_ms": gw_stats["p50_ms"],
            "serve_tokens_per_sec": gw_stats["tokens_per_sec"],
            "roundrobin_p99_ms": rr_stats["p99_ms"],
            "roundrobin_p50_ms": rr_stats["p50_ms"],
            "gateway_beats_rr":
                gw_stats["p99_ms"] < rr_stats["p99_ms"],
            "requests": N_REQ,
            "concurrency": N_THREADS,
            "max_new_tokens": MAX_NEW,
            "n_replicas": 3,
            "slow_replica_ms": SLOW_MS,
            "shed": gw.admission.shed_total,
            **paged,
            **spec,
        })
    finally:
        if client is not None:
            client.close()
        if gw is not None:
            gw.close()
        for r in regs:
            r.close()
        for s in servers:
            s.close()
        state.close()


def scale_main() -> None:
    """``make scale-bench``: the elastic-reconciler acceptance
    numbers (ISSUE 13) on a host-mesh fleet of control-plane replicas
    (FakeGeneratorActor — the reconciler and gateway cannot tell):

    - ``scale_up_latency_s``: wall seconds from the FIRST shed (the
      moment the gateway's hint stream turns urgent) to a second
      replica answering probes — the spike-to-capacity lag the warm
      pool and spawn path bound;
    - ``drain_lost_requests``: non-shed request failures while a
      replica is gracefully drained under continuous traffic (stop
      admitting → finish in-flight → deregister → exit). The
      acceptance bar is 0 — a drain that loses requests is a kill.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import threading

    import numpy as np

    from ptype_tpu.coord.core import CoordState
    from ptype_tpu.coord.local import LocalCoord
    from ptype_tpu.errors import ShedError
    from ptype_tpu.gateway import GatewayConfig, InferenceGateway
    from ptype_tpu.metrics import MetricsRegistry
    from ptype_tpu.reconciler import (FakeGeneratorActor, LocalLauncher,
                                      Reconciler, ReconcilerConfig)
    from ptype_tpu.registry import CoordRegistry

    PROMPT = np.zeros((1, 4), np.int32)
    state = CoordState(sweep_interval=0.1)
    registry = CoordRegistry(LocalCoord(state), lease_ttl=2.0)
    mreg = MetricsRegistry()
    launcher = LocalLauncher(
        registry, lambda: FakeGeneratorActor(delay_s=0.05),
        service="llm-scale")
    rec = Reconciler(
        registry, "llm-scale", launcher,
        cfg=ReconcilerConfig(min_replicas=1, max_replicas=3,
                             cooldown_s=0.2, vote_quorum=1,
                             tick_interval_s=0.02,
                             drain_deadline_s=15.0),
        metrics_registry=mreg)
    gw = None
    try:
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            rec.tick()
            if len(registry.nodes("llm-scale")) == 1:
                break
            time.sleep(0.02)
        gw = InferenceGateway(
            registry, "llm-scale",
            GatewayConfig(probe_interval_s=0.05, probe_timeout_s=1.0,
                          default_deadline_s=15.0, max_queue_depth=4,
                          per_replica_inflight=1))
        while gw.pool.n_healthy() < 1:
            time.sleep(0.02)
        rec._hints = gw.scale_hint
        rec.start()

        # ---- scale-up latency: burst one replica's worth of excess.
        first_shed = [None]
        lock = threading.Lock()

        def burst_worker(out):
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                try:
                    np.asarray(gw.generate(PROMPT, 4, deadline_s=5.0))
                    out.append(1)
                    return
                except ShedError as e:
                    with lock:
                        if first_shed[0] is None:
                            first_shed[0] = time.monotonic()
                    time.sleep(min(0.1, e.retry_after_s))
            out.append(0)

        done: list = []
        threads = [threading.Thread(target=burst_worker, args=(done,))
                   for _ in range(12)]
        for t in threads:
            t.start()
        scale_up_s = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if gw.pool.n_healthy() >= 2 and first_shed[0] is not None:
                scale_up_s = time.monotonic() - first_shed[0]
                break
            time.sleep(0.005)
        for t in threads:
            t.join(timeout=60)
        burst_answered = sum(done)

        # ---- drain under traffic: shrink back while firing.
        lost, drained_sheds, answered = [], [], []
        stop = threading.Event()

        def steady_worker():
            while not stop.is_set():
                try:
                    np.asarray(gw.generate(PROMPT, 4, deadline_s=5.0))
                    answered.append(1)
                except ShedError:
                    drained_sheds.append(1)
                    time.sleep(0.02)
                except Exception as e:  # noqa: BLE001 — the lost
                    lost.append(repr(e))  # bucket IS the metric

        steady = [threading.Thread(target=steady_worker)
                  for _ in range(4)]
        for t in steady:
            t.start()
        time.sleep(0.5)
        n_before = len(registry.nodes("llm-scale"))
        rec.desired = max(1, n_before - 1)
        deadline = time.monotonic() + 30
        while (len(registry.nodes("llm-scale")) >= n_before
               and time.monotonic() < deadline):
            time.sleep(0.05)
        time.sleep(0.5)  # keep firing through the post-drain fleet
        stop.set()
        for t in steady:
            t.join(timeout=30)

        _emit({
            "metric": "elastic scale-up latency (first shed -> new "
                      "replica answering; cpu host, control-plane "
                      "replicas)",
            "value": (round(scale_up_s, 3)
                      if scale_up_s is not None else None),
            "unit": "s",
            "scale_up_latency_s": (round(scale_up_s, 3)
                                   if scale_up_s is not None
                                   else None),
            "drain_lost_requests": len(lost),
            "drain_answered": len(answered),
            "drain_sheds_retried": len(drained_sheds),
            "burst_answered": burst_answered,
            "burst_size": 12,
            "scale_decisions": int(
                mreg.counter("scale.decisions").value),
            "spawns": int(mreg.counter("scale.spawns").value),
            "drains": int(mreg.counter("scale.drains").value),
            "drain_escalations": int(
                mreg.counter("scale.drain_escalations").value),
            "notes": {
                "scale_up_latency_s":
                    "wall from the first typed shed (urgent hint "
                    "onset) to pool.n_healthy()>=2 (spawned replica "
                    "answering probes); in-process spawn — OS-process "
                    "spawns add interpreter+import+compile, which the "
                    "warm pool exists to pre-pay",
                "drain_lost_requests":
                    "non-shed failures during a graceful drain under "
                    "4-thread continuous traffic; bar is 0 (sheds "
                    "re-route typed and are retried, never lost)",
            },
        })
        if lost:
            raise SystemExit(2)
    finally:
        if gw is not None:
            gw.close()
        rec.close(stop_fleet=True)
        launcher.close()
        state.close()


def traffic_main() -> None:
    """``make traffic-bench``: the open-loop traffic observatory
    acceptance numbers (ISSUE 19) on a host-mesh fleet of
    control-plane replicas (FakeGeneratorActor — the gateway,
    reconciler, and admission path are real; only the XLA forward is
    skipped, so the measured knee is a control-plane capacity, which
    is exactly what the frontier harness itself is being graded on):

    - the capacity frontier: ONE seeded trace replayed open-loop at
      >= 5 offered rates through gateway + pinned fleet; goodput
      (requests meeting the TTFT SLO) vs offered load, knee located
      (``traffic_knee_rps`` / ``traffic_goodput_at_knee_pct`` /
      ``traffic_ttft_p99_ms_open_loop``);
    - the diurnal-spike drill: the SAME seeded diurnal trace against
      a static fleet (min=max=1) and a reconciler-armed elastic
      fleet — the elastic fleet must hold the open-loop TTFT p99 SLO
      through the spike the static fleet measurably fails;
    - scale-up-latency vs burst steepness (elastic fleet, rising
      burst rates) and the shed-rate-vs-burn-budget curve off the
      static spike run's ledger.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from ptype_tpu.coord.core import CoordState
    from ptype_tpu.coord.local import LocalCoord
    from ptype_tpu.gateway import GatewayConfig, InferenceGateway
    from ptype_tpu.loadgen import (DriverConfig, OpenLoopDriver,
                                   TrafficLedger, gateway_target,
                                   shed_burn_curve, sweep,
                                   synth_trace)
    from ptype_tpu.metrics import MetricsRegistry
    from ptype_tpu.reconciler import (FakeGeneratorActor,
                                      LocalLauncher, Reconciler,
                                      ReconcilerConfig)
    from ptype_tpu.registry import CoordRegistry

    SEED = int(os.environ.get("PTYPE_TRAFFIC_SEED", "20260807"))
    SLO_TTFT_MS = 150.0     # steady-state SLO (frontier goodput)
    # The spike/burst drills price the scale-up transient too — the
    # requests that queue while the reconciler reacts are in the p99
    # (the drill-tier test pins the same split).
    SPIKE_SLO_TTFT_MS = 250.0
    DELAY_S = 0.02          # fake service time
    INFLIGHT = 2            # per-replica concurrency
    # => one replica is worth ~INFLIGHT/DELAY_S = 100 rps.

    def build_fleet(service, min_r, max_r, elastic):
        state = CoordState(sweep_interval=0.1)
        registry = CoordRegistry(LocalCoord(state), lease_ttl=2.0)
        mreg = MetricsRegistry()
        launcher = LocalLauncher(
            registry, lambda: FakeGeneratorActor(delay_s=DELAY_S),
            service=service)
        rec = Reconciler(
            registry, service, launcher,
            cfg=ReconcilerConfig(min_replicas=min_r,
                                 max_replicas=max_r,
                                 cooldown_s=0.2, vote_quorum=1,
                                 tick_interval_s=0.02,
                                 drain_deadline_s=15.0),
            metrics_registry=mreg)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            rec.tick()
            if len(registry.nodes(service)) >= min_r:
                break
            time.sleep(0.02)
        gw = InferenceGateway(
            registry, service,
            GatewayConfig(probe_interval_s=0.05, probe_timeout_s=1.0,
                          default_deadline_s=10.0,
                          max_queue_depth=64,
                          per_replica_inflight=INFLIGHT,
                          slo_ttft_p99_ms=SLO_TTFT_MS),
            metrics_registry=mreg)
        while gw.pool.n_healthy() < min_r:
            time.sleep(0.02)
        if elastic:
            rec._hints = gw.scale_hint
        rec.start()
        return state, launcher, rec, gw, mreg

    def teardown(state, launcher, rec, gw):
        gw.close()
        rec.close(stop_fleet=True)
        launcher.close()
        state.close()

    # ---- capacity frontier: pinned 2-replica fleet (~200 rps).
    fleet = build_fleet("llm-traffic", 2, 2, elastic=False)
    state, launcher, rec, gw, mreg = fleet
    try:
        trace = synth_trace(SEED, process="poisson", rate_rps=60.0,
                            duration_s=4.0)
        fr = sweep(trace, gateway_target(gw, deadline_s=5.0),
                   [40, 80, 120, 160, 240, 320],
                   slo_ttft_ms=SLO_TTFT_MS,
                   cfg=DriverConfig(max_inflight=256,
                                    deadline_s=5.0),
                   settle_s=0.4, registry=mreg)
        overload = TrafficLedger(slo_ttft_ms=SLO_TTFT_MS)
        OpenLoopDriver(trace.at_rate(320),
                       gateway_target(gw, deadline_s=5.0),
                       ledger=overload,
                       cfg=DriverConfig(max_inflight=256)).run()
        burn = shed_burn_curve(overload.summary())
    finally:
        teardown(state, launcher, rec, gw)

    # ---- diurnal-spike drill: same seeded trace, two fleets.
    spike_trace = synth_trace(SEED, process="diurnal",
                              duration_s=8.0, trough_rps=15.0,
                              peak_rps=180.0, sharpness=2.0)

    def spike_run(elastic):
        import threading
        svc = "llm-spike-e" if elastic else "llm-spike-s"
        st, la, rc, g, _ = build_fleet(svc, 1, 4 if elastic else 1,
                                       elastic=elastic)
        try:
            # Peak fleet size during the run — the trace ends in a
            # trough, so an elastic fleet has already scaled back
            # down by the time the driver returns.
            peak = [g.pool.n_healthy()]
            done = threading.Event()

            def watch():
                while not done.is_set():
                    peak[0] = max(peak[0], g.pool.n_healthy())
                    done.wait(0.05)

            w = threading.Thread(target=watch, daemon=True)
            w.start()
            led = TrafficLedger(slo_ttft_ms=SPIKE_SLO_TTFT_MS)
            OpenLoopDriver(spike_trace,
                           gateway_target(g, deadline_s=5.0),
                           ledger=led,
                           cfg=DriverConfig(max_inflight=256)).run()
            done.set()
            w.join(timeout=1.0)
            return led.summary(), peak[0]
        finally:
            teardown(st, la, rc, g)

    static_sum, _ = spike_run(elastic=False)
    elastic_sum, elastic_fleet_n = spike_run(elastic=True)

    # ---- scale-up latency vs burst steepness (elastic fleet).
    steepness_curve = []
    for burst_rps in (120.0, 240.0):
        st, la, rc, g, _ = build_fleet(
            f"llm-burst-{int(burst_rps)}", 1, 4, elastic=True)
        try:
            btrace = synth_trace(SEED, process="bursty",
                                 duration_s=4.0, base_rps=10.0,
                                 burst_rps=burst_rps,
                                 mean_on_s=2.0, mean_off_s=0.8)
            grown = [None]
            t0 = time.monotonic()

            def watch(g=g, grown=grown, t0=t0):
                while grown[0] is None:
                    if g.pool.n_healthy() >= 2:
                        grown[0] = time.monotonic() - t0
                        return
                    if time.monotonic() - t0 > 30:
                        return
                    time.sleep(0.01)

            import threading
            w = threading.Thread(target=watch, daemon=True)
            w.start()
            led = TrafficLedger(slo_ttft_ms=SPIKE_SLO_TTFT_MS)
            OpenLoopDriver(btrace,
                           gateway_target(g, deadline_s=5.0),
                           ledger=led,
                           cfg=DriverConfig(max_inflight=256)).run()
            w.join(timeout=1.0)
            steepness_curve.append({
                "burst_rps": burst_rps,
                "scale_up_s": (round(grown[0], 3)
                               if grown[0] is not None else None),
                "goodput_pct": round(
                    led.summary()["goodput_pct"], 1)})
        finally:
            teardown(st, la, rc, g)

    knee = fr.knee
    _emit({
        "metric": "open-loop capacity frontier knee (cpu host, "
                  "control-plane replicas, seeded trace replay)",
        "value": (round(fr.knee_rps, 1)
                  if fr.knee_rps is not None else None),
        "unit": "rps",
        "traffic_knee_rps": (round(fr.knee_rps, 1)
                             if fr.knee_rps is not None else None),
        "traffic_goodput_at_knee_pct": (
            round(knee.goodput_pct, 1) if knee else None),
        "traffic_ttft_p99_ms_open_loop": (
            round(knee.ttft_p99_ms, 1)
            if knee and knee.ttft_p99_ms is not None else None),
        "traffic_frontier": [p.as_dict() for p in fr.points],
        "traffic_knee_culprit_stage": (knee.culprit_stage
                                       if knee else None),
        "traffic_slo_bad_stages_at_knee": (
            dict(knee.slo_bad_stages) if knee else None),
        "traffic_seed": SEED,
        "traffic_spike_slo_ttft_ms": SPIKE_SLO_TTFT_MS,
        "traffic_spike_static_ttft_p99_ms": (
            round(static_sum["ttft_p99_ms"], 1)
            if static_sum["ttft_p99_ms"] is not None else None),
        "traffic_spike_elastic_ttft_p99_ms": (
            round(elastic_sum["ttft_p99_ms"], 1)
            if elastic_sum["ttft_p99_ms"] is not None else None),
        "traffic_spike_static_goodput_pct": round(
            static_sum["goodput_pct"], 1),
        "traffic_spike_elastic_goodput_pct": round(
            elastic_sum["goodput_pct"], 1),
        "traffic_spike_elastic_fleet": elastic_fleet_n,
        "traffic_scaleup_vs_steepness": steepness_curve,
        "traffic_shed_burn": burn,
        "notes": {
            "traffic_knee_rps":
                "highest offered rate with goodput >= 90% of "
                "offered; one seeded trace replayed at every rate "
                "(population identical, schedule compressed)",
            "traffic_ttft_p99_ms_open_loop":
                "ledger-measured open-loop TTFT p99 AT the knee "
                "(e2e stands in for TTFT on the non-streaming "
                "fake-replica path — a conservative upper bound)",
            "traffic_knee_culprit_stage":
                "WHY the knee is where it is: every SLO-bad request "
                "at the knee blamed on the stage with the largest "
                "budget overage (gateway stage split priced against "
                "the TTFT stage budgets); the mode of those blames",
            "spike_drill":
                "same seeded diurnal trace; static fleet (1 replica) "
                "vs reconciler-armed fleet (1..4) — elastic must "
                "hold TTFT p99 <= SLO where static fails",
        },
    })


def main() -> None:
    if "--worker" in sys.argv:
        worker_main()
        return
    if "--serve" in sys.argv:
        serve_main()
        return
    if "--scale" in sys.argv:
        scale_main()
        return
    if "--spec" in sys.argv:
        spec_main()
        return
    if "--disagg" in sys.argv:
        disagg_main()
        return
    if "--collectives" in sys.argv:
        collectives_main()
        return
    if "--hier" in sys.argv:
        hier_main()
        return
    if "--zero" in sys.argv:
        zero_main()
        return
    if "--profile" in sys.argv:
        profile_main()
        return
    if "--jitwatch" in sys.argv:
        jitwatch_main()
        return
    if "--traffic" in sys.argv:
        traffic_main()
        return
    if "--forensics" in sys.argv:
        forensics_main()
        return

    t_start = time.time()
    provisional = {
        "metric": "optimus-125M tokens/sec/chip", "value": None,
        "unit": "tokens/sec/chip", "vs_baseline": None,
        "provisional": True,
        "note": "bench starting; a later line supersedes this one",
    }
    _emit(provisional)  # a driver kill from here on never leaves an
    #                     empty tail (VERDICT r3 weak #1)

    errs: list[str] = []
    probe_ok = _backend_probe()
    if not probe_ok:
        # Wedged tunnel: land a real (labeled) number FIRST, then still
        # give the TPU one short shot in case it returned mid-bench.
        errs.append(f"backend probe hung/failed ({PROBE_TIMEOUT}s)")
        provisional["note"] = errs[-1] + "; running cpu fallback"
        _emit(provisional)
        emitted = _cpu_fallback(errs)
        line, err, fatal = _attempt({"PTYPE_BENCH_ATTN": "xla"},
                                    timeout=RETRY_TIMEOUT)
        if line is not None and json.loads(line).get("value") is not None:
            _finalize(line)  # supersedes the cpu line
            return
        if fatal and line is not None and not emitted:
            # The worker's own structured "all plans failed" record is
            # the authoritative diagnosis — surface it, as the healthy
            # path does.
            _emit(json.loads(line))
            raise SystemExit(2)
        if err:
            errs.append(f"tpu retry: {err}")
        if emitted:
            return  # cpu line already stands as the record
        _emit({**provisional, "provisional": False,
               "error": " ; ".join(errs)[-800:]})
        raise SystemExit(2)

    # Healthy probe: full ladder, then a short dense-only retry, then
    # the CPU fallback. Every attempt updates the tail.
    for i, (extra, cap) in enumerate((
            (None, ATTEMPT_TIMEOUT),
            ({"PTYPE_BENCH_ATTN": "xla"}, RETRY_TIMEOUT))):
        line, err, fatal = _attempt(extra, timeout=cap)
        if fatal:
            _emit(json.loads(line))
            raise SystemExit(2)
        if line is not None:
            _finalize(line)
            return
        errs.append(err)
        provisional["note"] = (
            f"attempt {i + 1} failed after {int(time.time() - t_start)}s: "
            + err[-300:])
        _emit(provisional)

    if _cpu_fallback(errs):
        return
    _emit({**provisional, "provisional": False,
           "error": " ; ".join(errs)[-800:]})
    raise SystemExit(2)


if __name__ == "__main__":
    main()
