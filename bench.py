"""Headline benchmark: optimus-125M data-parallel training throughput.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``

The metric is tokens/sec/chip on the north-star config (BASELINE.json:
"optimus-125M tokens/sec/chip"); ``vs_baseline`` is achieved MFU divided
by the 0.30 MFU target (the only quantitative baseline the reference
world defines — SURVEY.md §6: the reference publishes no numbers).

Reliability contract (VERDICT r1 weak #1: the bench must never zero out
the round because backend init was flaky once): the measurement runs in
a fresh ``--worker`` subprocess — JAX caches backend-init *failure*
in-process, so retries only mean anything in a new interpreter. The
orchestrator retries TPU init with backoff, falls back to an explicitly
labeled CPU smoke run if the TPU never comes up, and always emits a
JSON line (with an ``error`` field in the worst case) instead of a
traceback.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

MFU_TARGET = 0.30  # BASELINE.json north_star: ">=30% MFU on v5e-8"

#: Backoff schedule (seconds) between fresh-process TPU attempts.
RETRY_DELAYS = (0, 15, 45)
#: First-attempt cap, sized for the worst case of the 5-rung ladder (a
#: slow-failing flash regression can burn ~5 min per flash rung before
#: the dense-xla rungs even start).
WORKER_TIMEOUT = 2400
#: Short cap applied to a retry only when the PREVIOUS attempt timed
#: out (a hung tunnel hangs again; don't burn 3 × WORKER_TIMEOUT on
#: it). A retry after a fast transient crash keeps the full budget —
#: it may legitimately need the whole ladder.
RETRY_TIMEOUT = 600


# ----------------------------------------------------------------- worker


def _run(cfg, devices, per_chip_batch, seq, steps, warmup):
    import jax

    from ptype_tpu.parallel.mesh import build_mesh
    from ptype_tpu.train.data import synthetic_batches
    from ptype_tpu.train.trainer import Trainer

    n_chips = len(devices)
    mesh = build_mesh({"data": n_chips}, devices=devices)
    trainer = Trainer(cfg, mesh, sync_every=0)
    batch = per_chip_batch * n_chips
    stream = synthetic_batches(cfg.vocab_size, batch, seq)

    for _ in range(warmup):
        out = trainer.step(next(stream))
    trainer.sync()  # compile + warmup fully drained before the clock

    t0 = time.perf_counter()
    tokens = 0
    for _ in range(steps):
        out = trainer.step(next(stream))
        tokens += batch * seq
    jax.block_until_ready(out["loss"])  # steps dispatch async; drain
    dt = time.perf_counter() - t0
    return out, tokens, dt


def worker_main() -> None:
    import jax

    from ptype_tpu.models import transformer as tfm

    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    n_chips = len(devices)

    # (per-chip batch, seq, steps, warmup, remat, attn). Flash attention
    # leads the ladder (activation memory linear in S; larger batches
    # feed the MXU) but the LAST rung is attn_impl="xla": a flash-kernel
    # regression must degrade to a dense-attention baseline number, never
    # zero the round (VERDICT r2 weak #2 — round 2 emitted nothing
    # because every rung shared the one broken kernel).
    # remat is "dots" | True | False: "dots" = jax.checkpoint with the
    # dots-saveable policy — the round-3 sweep's best plan (0.448 MFU
    # vs 0.445 no-remat, 0.434 b=24, 0.328 scan_unroll=2; b=32 no-remat
    # crashes the v5e remote-compile helper, which is why the b=16
    # rung leads).
    if on_tpu:
        preset_name = "optimus-125m"
        plans = [(16, 1024, 30, 3, "dots", "flash"),
                 (16, 1024, 30, 3, False, "flash"),
                 (8, 1024, 20, 3, True, "flash"),
                 (16, 1024, 30, 3, False, "xla"),
                 (8, 1024, 20, 3, True, "xla")]
    else:
        preset_name = "tiny"
        plans = [(4, 128, 5, 1, False, "xla")]
    # A hang-mode flash regression times out the whole attempt before
    # the dense rungs run; the orchestrator retries with this env set so
    # the retry starts at the xla rungs instead of hanging again.
    if os.environ.get("PTYPE_BENCH_ATTN") == "xla":
        plans = [p for p in plans if p[5] == "xla"] or plans

    # The bench runs unattended: fall back to smaller batches (and remat
    # as a last resort) rather than dying on an HBM OOM.
    last_err = None
    for pcb, seq, steps, warmup, remat, attn in plans:
        try:
            cfg = tfm.preset(
                preset_name, remat=bool(remat), attn_impl=attn,
                remat_policy="dots" if remat == "dots" else "none")
            out, tokens, dt = _run(cfg, devices, pcb, seq, steps, warmup)
            batch_used, seq_used, attn_used = pcb * n_chips, seq, attn
            remat_used = remat
            break
        except Exception as e:  # noqa: BLE001 — report, try next plan
            last_err = e
    else:
        print(json.dumps({
            "metric": "optimus-125M tokens/sec/chip",
            "value": None, "unit": "tokens/sec/chip", "vs_baseline": None,
            "error": f"all plans failed: {last_err!r:.500}",
        }))
        raise SystemExit(3)

    tps_chip = tokens / dt / n_chips
    from ptype_tpu.metrics import device_peak_tflops, mfu as mfu_of

    achieved_mfu = mfu_of(
        tokens / dt, tfm.flops_per_token(cfg, seq_used), n_chips,
        device_peak_tflops(devices[0]),
    )

    # Second BASELINE metric: Store push/pull == allreduce bandwidth.
    # On one chip there is no ICI to measure — report why it's absent
    # rather than a bare null (VERDICT r1 weak #7).
    store_gbps = None
    store_note = None
    if n_chips > 1:
        from ptype_tpu.parallel.collectives import measure_allreduce_gbps
        from ptype_tpu.parallel.mesh import build_mesh

        try:
            store_gbps = round(measure_allreduce_gbps(
                build_mesh({"data": n_chips}, devices=devices),
                mbytes=64 if on_tpu else 4), 2)
        except Exception as e:  # noqa: BLE001 — secondary, best-effort
            store_note = f"failed: {e!r:.200}"
    else:
        store_note = "skipped: 1 chip (no ICI)"
    print(json.dumps({
        "metric": "optimus-125M tokens/sec/chip"
        if on_tpu else "optimus-tiny tokens/sec/chip (cpu smoke)",
        "value": round(tps_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(achieved_mfu / MFU_TARGET, 4),
        "mfu": round(achieved_mfu, 4),
        "attn": attn_used,
        "remat": str(remat_used),
        "n_chips": n_chips,
        "batch": batch_used,
        "seq": seq_used,
        "store_allreduce_gbps": store_gbps,
        "store_allreduce_note": store_note,
        "final_loss": round(float(out["loss"]), 4),
    }))


# ------------------------------------------------------------ orchestrator


def _attempt(extra_env: dict | None = None,
             timeout: int = WORKER_TIMEOUT) -> tuple[str | None, str, bool]:
    """Run one fresh worker process.

    Returns (json_line | None, err_tail, fatal). ``fatal`` means the
    worker ran to a structured verdict (rc=3: every plan failed
    deterministically) — retrying the identical ladder cannot help, and
    the worker's own JSON error line is the authoritative record.
    """
    env = dict(os.environ)
    if extra_env:
        env.update(extra_env)
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            capture_output=True, text=True, timeout=timeout,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return None, f"worker timed out after {timeout}s", False
    lines = [ln for ln in p.stdout.splitlines()
             if ln.startswith("{") and '"metric"' in ln]
    if p.returncode == 0 and lines:
        return lines[-1], "", False
    if p.returncode == 3 and lines:
        return lines[-1], "worker: all plans failed", True
    tail = (p.stderr or p.stdout or "").strip().splitlines()[-6:]
    return None, " | ".join(tail)[-800:], False


def _backend_probe(timeout: int = 120) -> bool:
    """True when the accelerator backend initializes in a fresh
    process. A wedged device tunnel HANGS backend init (observed on
    this harness for hours); without this probe every ladder attempt
    would burn its full WORKER_TIMEOUT discovering the same hang, and
    a driver-side cap could zero the round before the CPU fallback."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, timeout=timeout, env=dict(os.environ))
        return p.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    if "--worker" in sys.argv:
        worker_main()
        return

    errs: list[str] = []
    # A hung/broken backend shortens every attempt's budget up front:
    # the retries still run (the tunnel may come back between them),
    # but the worst case stays ~3×RETRY_TIMEOUT + CPU fallback instead
    # of 3×WORKER_TIMEOUT.
    prev_timed_out = not _backend_probe()
    if prev_timed_out:
        errs.append("backend probe hung/failed; short attempt budgets")
    for delay in RETRY_DELAYS:
        if delay:
            time.sleep(delay)
        # After a timed-out attempt, assume a hang-mode kernel/compile
        # regression: retry only the dense-xla rungs, shorter-fused, so
        # the round still gets a baseline number.
        line, err, fatal = _attempt(
            extra_env={"PTYPE_BENCH_ATTN": "xla"} if prev_timed_out
            else None,
            timeout=RETRY_TIMEOUT if prev_timed_out else WORKER_TIMEOUT)
        prev_timed_out = prev_timed_out or "timed out" in err
        if fatal:
            # Deterministic failure with a structured record — surface
            # the worker's own error line, don't re-run the ladder.
            print(line)
            raise SystemExit(2)
        if line is not None:
            print(line)
            return
        errs.append(err)

    # TPU never came up: labeled CPU fallback so the round still has a
    # (clearly non-headline) number plus the real error.
    line, err, _ = _attempt({"JAX_PLATFORMS": "cpu"})
    if line is not None:
        rec = json.loads(line)
        rec["fallback"] = "cpu"
        rec["error"] = (f"tpu init failed after {len(RETRY_DELAYS)} "
                        f"attempts: {errs[-1]}")
        print(json.dumps(rec))
        return
    print(json.dumps({
        "metric": "optimus-125M tokens/sec/chip", "value": None,
        "unit": "tokens/sec/chip", "vs_baseline": None,
        "error": f"tpu: {errs[-1]} ; cpu fallback: {err}",
    }))
    raise SystemExit(2)


if __name__ == "__main__":
    main()
