"""Headline benchmark: optimus-125M data-parallel training throughput.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``

The metric is tokens/sec/chip on the north-star config (BASELINE.json:
"optimus-125M tokens/sec/chip"); ``vs_baseline`` is achieved MFU divided
by the 0.30 MFU target (the only quantitative baseline the reference
world defines — SURVEY.md §6: the reference publishes no numbers).

On TPU this runs the real 125M model with a chip-sized batch; on CPU
(driver smoke runs, local dev) it scales the model and step count down so
the line still prints in seconds.
"""

from __future__ import annotations

import json
import time

import jax

from ptype_tpu.models import transformer as tfm
from ptype_tpu.parallel.mesh import build_mesh
from ptype_tpu.train.data import synthetic_batches
from ptype_tpu.train.trainer import Trainer

MFU_TARGET = 0.30  # BASELINE.json north_star: ">=30% MFU on v5e-8"


def _run(cfg, devices, per_chip_batch, seq, steps, warmup):
    n_chips = len(devices)
    mesh = build_mesh({"data": n_chips}, devices=devices)
    trainer = Trainer(cfg, mesh)
    batch = per_chip_batch * n_chips
    stream = synthetic_batches(cfg.vocab_size, batch, seq)

    for _ in range(warmup):
        trainer.step(next(stream))

    t0 = time.perf_counter()
    tokens = 0
    for _ in range(steps):
        out = trainer.step(next(stream))
        tokens += batch * seq
    dt = time.perf_counter() - t0
    return out, tokens, dt


def main() -> None:
    devices = jax.devices()
    on_tpu = devices[0].platform == "tpu"
    n_chips = len(devices)

    if on_tpu:
        cfg = tfm.preset("optimus-125m")
        plans = [(16, 1024, 20, 3), (8, 1024, 20, 3)]
    else:
        cfg = tfm.preset("tiny")
        plans = [(4, 128, 5, 1)]

    # The bench runs unattended: fall back to the smaller batch (and
    # remat as a last resort) rather than dying on an HBM OOM.
    last_err = None
    for i, (pcb, seq, steps, warmup) in enumerate(plans):
        try:
            run_cfg = cfg if i == 0 else tfm.preset(
                "optimus-125m", remat=True) if on_tpu else cfg
            out, tokens, dt = _run(run_cfg, devices, pcb, seq, steps,
                                   warmup)
            batch_used, seq_used = pcb * n_chips, seq
            break
        except Exception as e:  # noqa: BLE001 — report, try next plan
            last_err = e
    else:
        raise SystemExit(f"bench: all plans failed: {last_err}")

    tps_chip = tokens / dt / n_chips
    from ptype_tpu.metrics import device_peak_tflops, mfu as mfu_of

    achieved_mfu = mfu_of(
        tokens / dt, tfm.flops_per_token(cfg, seq_used), n_chips,
        device_peak_tflops(devices[0]),
    )

    # Second BASELINE metric: Store push/pull == allreduce bandwidth.
    store_gbps = None
    if n_chips > 1:
        from ptype_tpu.parallel.collectives import measure_allreduce_gbps

        try:
            store_gbps = round(measure_allreduce_gbps(
                build_mesh({"data": n_chips}, devices=devices),
                mbytes=64 if on_tpu else 4), 2)
        except Exception:  # noqa: BLE001 — secondary metric, best-effort
            pass
    print(json.dumps({
        "metric": "optimus-125M tokens/sec/chip"
        if on_tpu else "optimus-tiny tokens/sec/chip (cpu smoke)",
        "value": round(tps_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(achieved_mfu / MFU_TARGET, 4),
        "mfu": round(achieved_mfu, 4),
        "n_chips": n_chips,
        "batch": batch_used,
        "seq": seq_used,
        "store_allreduce_gbps": store_gbps,
        "final_loss": out["loss"],
    }))


if __name__ == "__main__":
    main()
