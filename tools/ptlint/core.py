"""ptlint core: findings, file context, rule registry, suppressions.

One parse per file: :class:`FileContext` owns the source, the AST and
the path taxonomy; every registered rule whose ``applies`` predicate
accepts the context runs over it and returns :class:`Finding`\\ s.
Suppression handling is central (rules never see comments):

- ``# noqa`` on the finding line suppresses everything there (the
  legacy escape hatch, kept so old call sites stay valid);
- ``# ptlint: disable=PT013`` (comma-separated codes) suppresses the
  listed codes on that line, and MUST carry a justification after the
  code list (``# ptlint: disable=PT014 -- probe RPC is deadline-bounded``)
  or it is itself a finding (PTL002);
- a disable comment whose codes produced no finding on that line is an
  unused suppression (PTL001) — suppressions rot when the code under
  them changes, and a stale one silently disables the NEXT real
  finding on the line.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import sys
import tokenize

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: The directive comment shape (justification separator is free-form:
#: anything after the code list counts). Anchored at the start of the
#: COMMENT token, so a comment QUOTING a directive is prose.
_DISABLE_RE = re.compile(
    r"^#\s*ptlint:\s*disable=([A-Za-z0-9_,]+)(.*)$")


class Finding:
    """One diagnostic: ``path:line: code message``."""

    __slots__ = ("path", "line", "code", "message")

    def __init__(self, path: str, line: int, code: str, message: str):
        self.path = path
        self.line = int(line)
        self.code = code
        self.message = message

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def to_json(self) -> dict:
        return {"path": self.path, "line": self.line,
                "code": self.code, "message": self.message}

    def __repr__(self) -> str:
        return f"Finding({self.format()!r})"


class FileContext:
    """Everything a rule needs about one file, parsed once."""

    def __init__(self, path: str, src: str, tree: ast.AST):
        self.path = path
        self.src = src
        self.tree = tree
        self.lines = src.splitlines()
        norm = os.path.normpath(path)
        self.parts = norm.split(os.sep)
        self.basename = os.path.basename(path)
        self.is_init = self.basename == "__init__.py"

    # -- path taxonomy helpers (the old checker's dispatch, named)

    def in_dir(self, name: str) -> bool:
        return name in self.parts

    @property
    def in_pkg(self) -> bool:
        return "ptype_tpu" in self.parts

    def finding(self, node_or_line, code: str, message: str) -> Finding:
        line = (node_or_line if isinstance(node_or_line, int)
                else getattr(node_or_line, "lineno", 0))
        return Finding(self.path, line, code, message)


class Rule:
    """One registered pass: stable code, doc line, gate, check."""

    __slots__ = ("code", "summary", "applies", "check")

    def __init__(self, code, summary, applies, check):
        self.code = code
        self.summary = summary
        self.applies = applies
        self.check = check


#: code -> Rule. Codes are stable IDs: docs/LINTING.md catalogues
#: them, suppressions name them, and tests pin them.
RULES: dict[str, Rule] = {}


def rule(code: str, summary: str, applies=None):
    """Decorator: register ``check(ctx) -> list[Finding]`` under a
    stable code. ``applies(ctx) -> bool`` gates by path (default:
    every file)."""

    def wrap(fn):
        if code in RULES:
            raise ValueError(f"duplicate ptlint rule code {code!r}")
        RULES[code] = Rule(code, summary, applies or (lambda ctx: True),
                           fn)
        return fn

    return wrap


# ------------------------------------------------------------ suppression


def _parse_suppressions(ctx: FileContext) -> dict[int, tuple[set, bool]]:
    """lineno -> (codes, justified) for every ``ptlint: disable``
    comment. Real COMMENT tokens only (tokenize): a directive QUOTED
    in a docstring — this docstring, the rule catalogue, a test
    fixture string — is prose, not a suppression."""
    out: dict[int, tuple[set, bool]] = {}
    try:
        tokens = tokenize.generate_tokens(
            io.StringIO(ctx.src).readline)
        comments = [(t.start[0], t.string) for t in tokens
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError):
        return out
    for lineno, text in comments:
        m = _DISABLE_RE.search(text)
        if m is None:
            continue
        codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
        justification = m.group(2).strip(" -—:\t")
        out[lineno] = (codes, bool(justification))
    return out


def _apply_suppressions(ctx: FileContext,
                        raw: list[Finding]) -> list[Finding]:
    """Drop suppressed findings; add PTL001 (unused suppression) and
    PTL002 (suppression without justification) findings."""
    disables = _parse_suppressions(ctx)
    used: dict[int, set] = {i: set() for i in disables}
    kept: list[Finding] = []
    for f in raw:
        line = ctx.lines[f.line - 1] if 0 < f.line <= len(ctx.lines) \
            else ""
        if "noqa" in line:
            continue
        codes, _ = disables.get(f.line, (set(), True))
        if f.code in codes:
            used[f.line].add(f.code)
            continue
        kept.append(f)
    for lineno, (codes, justified) in disables.items():
        unused = codes - used.get(lineno, set())
        # Meta-codes can't be pre-suppressed by themselves; a disable
        # line may legitimately pre-arm a code for a finding the rule
        # only raises on SOME configurations — no: unused is unused.
        if unused:
            kept.append(Finding(
                ctx.path, lineno, "PTL001",
                f"unused suppression for "
                f"{', '.join(sorted(unused))} — no such finding on "
                f"this line; a stale disable silently eats the next "
                f"real one (delete it)"))
        if not justified:
            kept.append(Finding(
                ctx.path, lineno, "PTL002",
                f"suppression for {', '.join(sorted(codes))} carries "
                f"no justification — write WHY after the code list "
                f"(`# ptlint: disable=PTxxx -- reason`)"))
    return kept


# --------------------------------------------------------------- checking


def check_file_findings(path: str) -> list[Finding]:
    """Run every applicable rule over one file; suppressions applied."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "E999", str(e.msg))]
    ctx = FileContext(path, src, tree)
    raw: list[Finding] = []
    for r in RULES.values():
        if r.applies(ctx):
            raw.extend(r.check(ctx))
    out = _apply_suppressions(ctx, raw)
    # De-duplicate (identical finding from overlapping walks), keep
    # first-seen order, then sort by line for stable output.
    seen: set[str] = set()
    uniq = []
    for f in out:
        key = f.format()
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    uniq.sort(key=lambda f: (f.line, f.code))
    return uniq


def check_file(path: str, findings: list[str]) -> None:
    """The tools/lint.py-compatible surface: append formatted
    ``path:line: code message`` strings."""
    findings.extend(f.format() for f in check_file_findings(path))


def iter_py(paths: list[str]):
    for root in paths:
        if os.path.isfile(root):
            yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for f in sorted(filenames):
                if f.endswith(".py"):
                    yield os.path.join(dirpath, f)


def run_paths(paths: list[str]) -> tuple[list[Finding], int]:
    """(findings, files checked) over files/directories."""
    findings: list[Finding] = []
    n = 0
    for path in iter_py(paths):
        n += 1
        findings.extend(check_file_findings(path))
    return findings, n


def main(argv: list[str]) -> int:
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    paths = argv or [os.path.join(REPO, "ptype_tpu"),
                     os.path.join(REPO, "tests"),
                     os.path.join(REPO, "examples"),
                     os.path.join(REPO, "bench.py"),
                     os.path.join(REPO, "__graft_entry__.py"),
                     os.path.join(REPO, "tools")]
    findings, n = run_paths(paths)
    if as_json:
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
    print(f"ptlint: {n} files, {len(findings)} findings, "
          f"{len(RULES)} rules", file=sys.stderr)
    return 1 if findings else 0
