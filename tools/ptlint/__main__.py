"""CLI entry: ``python -m tools.ptlint [--json] [paths...]`` from the
repo root (tools/ is a PEP 420 namespace package), or ``python -m
ptlint`` with tools/ on PYTHONPATH — both resolve to the same package.
"""

import sys

if __package__ in (None, ""):  # executed as a bare directory/script
    import os

    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from ptlint import main
else:
    from . import main

if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
