"""PT013/PT014/PT015 — the concurrency passes, tuned to this repo's
real defect history (drain-gate TOCTOU, control-RPC-held-under-lock,
zombie threads — the classes PR 2 and PR 12 fixed by hand).

All three ride the shared lock-context walker in :mod:`.scopes`; the
conventions they encode:

- a lock is anything whose name looks like one (``self._lock``,
  ``r.lock``, ``self._cond`` — see :func:`scopes.is_lockish`);
- ``*_locked`` methods are caller-holds-the-lock helpers (the house
  convention: ``_sample_locked``, ``_drain_ttft_locked``) and are
  exempt from PT013's bare-access check;
- ``__init__`` is exempt too: construction happens-before publication.
"""

from __future__ import annotations

import ast

from .core import FileContext, Finding, rule
from .scopes import (
    ContextWalker,
    ImportMap,
    is_lockish,
    terminal_name,
    unparse,
)

# --------------------------------------------------------------- PT013

#: Methods whose attribute accesses never need the lock: construction
#: happens-before publication, and ``*_locked`` helpers document that
#: their CALLER holds the lock.
_PT013_EXEMPT = ("__init__", "__new__", "__del__")

#: Constructors whose product is itself thread-safe (or is the
#: synchronization): an attribute holding one of these needs no lock.
_SYNC_CTORS = frozenset({
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue", "local",
    "WeakSet", "WeakValueDictionary",
    # the repo's own tracked-lock seam (ptype_tpu.lockcheck)
    "lock", "rlock", "condition",
})


class _Access:
    __slots__ = ("attr", "method", "line", "locks", "store")

    def __init__(self, attr, method, line, locks, store):
        self.attr = attr
        self.method = method
        self.line = line
        self.locks = locks      # frozenset of held self-lock attrs
        self.store = store


class _MethodWalker(ContextWalker):
    """Collect per-attribute accesses of one method (nested closures
    included — a spawn thread's body mutates the same ``self``)."""

    def __init__(self, method_name: str, self_name: str, out: list):
        super().__init__()
        self.method = method_name
        self.self_name = self_name
        self.out = out

    def _self_locks(self) -> frozenset:
        held = set()
        for h in self.held_locks:
            # `with self._lock:` — held self-attribute locks only;
            # foreign locks (`with r.lock:`) don't guard self state.
            if h.expr == f"{self.self_name}.{h.name}":
                held.add(h.name)
        return frozenset(held)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (isinstance(node.value, ast.Name)
                and node.value.id == self.self_name):
            self.out.append(_Access(
                node.attr, self.method, node.lineno,
                self._self_locks(),
                isinstance(node.ctx, (ast.Store, ast.Del))))
        self.generic_visit(node)


def _class_method_names(cls: ast.ClassDef) -> set[str]:
    names = set()
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(stmt.name)
    return names


def _self_arg(fn) -> str | None:
    args = fn.args.posonlyargs + fn.args.args
    return args[0].arg if args else None


def _sync_attrs(cls: ast.ClassDef, self_name_by_method: dict) -> set:
    """Attributes assigned a synchronization/thread-safe object
    anywhere in the class (usually ``__init__``)."""
    out = set()
    for stmt in cls.body:
        if not isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        self_name = self_name_by_method.get(stmt.name)
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Assign):
                continue
            if not (isinstance(node.value, ast.Call)
                    and terminal_name(node.value.func) in _SYNC_CTORS):
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == self_name):
                    out.add(t.attr)
    return out


def _init_only_methods(methods, self_by_method) -> set[str]:
    """Methods reachable ONLY from ``__init__``/``__new__``: their
    accesses happen-before the object is published to other threads,
    so they need no lock (fixpoint over the in-class self-call
    graph). A method with no in-class caller is public API and stays
    accountable."""
    callers: dict[str, set] = {}
    for m in methods:
        self_name = self_by_method.get(m.name)
        for node in ast.walk(m):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == self_name):
                callers.setdefault(node.func.attr, set()).add(m.name)
    exempt = {"__init__", "__new__"}
    changed = True
    while changed:
        changed = False
        for m in methods:
            if m.name in exempt:
                continue
            cs = callers.get(m.name)
            if cs and cs <= exempt:
                exempt.add(m.name)
                changed = True
    return exempt - {"__init__", "__new__"}


def _check_class_pt013(ctx: FileContext, cls: ast.ClassDef,
                       findings: list[Finding]) -> None:
    methods = [stmt for stmt in cls.body
               if isinstance(stmt, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))]
    method_names = _class_method_names(cls)
    self_by_method = {m.name: _self_arg(m) for m in methods}
    sync_attrs = _sync_attrs(cls, self_by_method)
    init_only = _init_only_methods(methods, self_by_method)

    accesses: list = []
    for m in methods:
        self_name = self_by_method.get(m.name)
        if not self_name:
            continue  # staticmethod-shaped: no self state
        w = _MethodWalker(m.name, self_name, accesses)
        w.visit(m)

    # attr -> observed facts across NON-exempt methods.
    locked_by: dict[str, set] = {}
    bare: dict[str, list] = {}
    stored_outside_init: set[str] = set()
    for a in accesses:
        attr = a.attr
        if (attr in method_names or attr in sync_attrs
                or is_lockish(attr)):
            continue
        exempt = (a.method in _PT013_EXEMPT
                  or a.method in init_only
                  or a.method.endswith("_locked"))
        if a.store and a.method not in ("__init__", "__new__"):
            stored_outside_init.add(attr)
        if exempt:
            continue
        if a.locks:
            locked_by.setdefault(attr, set()).update(a.locks)
        else:
            bare.setdefault(attr, []).append(a)

    for attr in sorted(locked_by):
        if attr not in bare or attr not in stored_outside_init:
            # Never guarded anywhere, or effectively immutable after
            # construction (only __init__ writes it): not shared
            # mutable state the lock is protecting.
            continue
        locks = "/".join(sorted(f"self.{name}"
                                for name in locked_by[attr]))
        # One finding per (attr, method): the first bare access in
        # each offending method, so a fix or a suppression is local.
        first_in_method: dict[str, _Access] = {}
        for a in bare[attr]:
            cur = first_in_method.get(a.method)
            if cur is None or a.line < cur.line:
                first_in_method[a.method] = a
        guarded_in = sorted({a.method for a in accesses
                             if getattr(a, "attr", None) == attr
                             and a.locks})
        for m, a in sorted(first_in_method.items(),
                           key=lambda kv: kv[1].line):
            findings.append(Finding(
                ctx.path, a.line, "PT013",
                f"attribute 'self.{attr}' is guarded by {locks} in "
                f"{', '.join(guarded_in[:3])} but accessed bare in "
                f"{m} — check-then-act on it races the guarded "
                f"writers (the drain-gate TOCTOU class); take the "
                f"lock, or rename the method '*_locked' if the "
                f"caller holds it"))


@rule("PT013",
      "lock-discipline: attribute guarded in some methods, bare in "
      "others",
      applies=lambda ctx: ctx.in_pkg and ctx.basename != "lockcheck.py")
def check_pt013(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            _check_class_pt013(ctx, node, findings)
    return findings


# --------------------------------------------------------------- PT014

#: Call terminal names that block on I/O or another thread: dialing,
#: wire sends/receives, synchronous RPC, future waits, subprocess.
_BLOCKING_VERBS = frozenset({
    "dial", "_dial", "create_connection", "send_msg", "recv_msg",
    "call", "_call", "result", "communicate", "check_call",
    "check_output", "Popen", "getaddrinfo", "connect", "accept",
})
_SUBPROCESS_FNS = frozenset({
    "run", "call", "check_call", "check_output", "Popen",
})
#: Receiver names that mark a ``.join`` as a THREAD join (str.join is
#: the overwhelmingly common false positive this filter removes).
_THREADISH = ("thread", "proc", "process", "worker", "reader",
              "watcher")


class _Pt014Walker(ContextWalker):
    def __init__(self, ctx, findings):
        super().__init__()
        self.ctx = ctx
        self.findings = findings
        self.imports = ImportMap(ctx.tree)
        #: Names assigned ``threading.Thread(...)`` per function —
        #: the lightweight dataflow that makes `t.join()` a thread
        #: join even without a thread-ish name.
        self.thread_vars: list[set] = [set()]

    def _fn(self, node) -> None:
        self.thread_vars.append(set())
        super()._fn(node)
        self.thread_vars.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = _fn

    def visit_Assign(self, node: ast.Assign) -> None:
        if (isinstance(node.value, ast.Call)
                and terminal_name(node.value.func) == "Thread"):
            for t in node.targets:
                self.thread_vars[-1].add(unparse(t))
        self.generic_visit(node)

    def _flag(self, node, what: str) -> None:
        lock = self.held_locks[-1]
        self.findings.append(self.ctx.finding(
            node, "PT014",
            f"blocking call {what} while holding '{lock.expr}' — "
            f"every other acquirer stalls for the call's full "
            f"latency (dial timeouts, sleeps, subprocess waits); "
            f"move the call outside the critical section and "
            f"publish its result under the lock (the PR 12 "
            f"control-RPC-under-lock class)"))

    def _is_thread_join(self, recv: ast.expr, node: ast.Call) -> bool:
        if isinstance(recv, ast.Constant):
            return False  # ", ".join(...)
        name = (terminal_name(recv) or "").lower()
        if any(k in name for k in _THREADISH):
            return True
        if any(kw.arg == "timeout" for kw in node.keywords):
            return True
        return unparse(recv) in self.thread_vars[-1]

    def visit_Call(self, node: ast.Call) -> None:
        if self.holding():
            fn = node.func
            name = terminal_name(fn)
            if isinstance(fn, ast.Attribute):
                recv = fn.value
                if name == "sleep":
                    self._flag(node, f"{unparse(fn)}()")
                elif name == "wait" and not self.holds_expr(
                        unparse(recv)):
                    # cond.wait() while holding cond is the condition-
                    # variable protocol, not a blocked hold.
                    self._flag(node, f"{unparse(fn)}()")
                elif name == "join" and self._is_thread_join(recv,
                                                             node):
                    self._flag(node, f"{unparse(fn)}()")
                elif (isinstance(recv, ast.Name)
                        and recv.id == "subprocess"
                        and name in _SUBPROCESS_FNS):
                    self._flag(node, f"subprocess.{name}()")
                elif (isinstance(recv, ast.Name)
                        and recv.id == "chaos"
                        and name in ("hit", "note_ok")):
                    self._flag(node, f"chaos.{name}() (the seam may "
                               f"inject a delay)")
                elif name in _BLOCKING_VERBS:
                    self._flag(node, f"{unparse(fn)}()")
            elif isinstance(fn, ast.Name):
                src = self.imports.from_names.get(fn.id)
                if fn.id == "sleep" or (
                        src is not None and src == ("time", "sleep")):
                    self._flag(node, f"{fn.id}()")
                elif src is not None and src[0] == "subprocess" \
                        and src[1] in _SUBPROCESS_FNS:
                    self._flag(node, f"{fn.id}() (subprocess)")
                elif fn.id == "create_connection" or (
                        src is not None
                        and src[1] == "create_connection"):
                    self._flag(node, f"{fn.id}()")
        self.generic_visit(node)


@rule("PT014", "blocking call under a held lock",
      applies=lambda ctx: ctx.in_pkg and ctx.basename not in (
          "lockcheck.py",))
def check_pt014(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _Pt014Walker(ctx, findings).visit(ctx.tree)
    return findings


# --------------------------------------------------------------- PT015


class _ThreadBirth:
    __slots__ = ("node", "target", "cls", "fn", "daemon")

    def __init__(self, node, target, cls, fn):
        self.node = node
        self.target = target  # unparse of the assignment target, or None
        self.cls = cls        # enclosing ClassDef name, or None
        self.fn = fn          # enclosing function name, or None
        self.daemon = False


class _Pt015Walker(ast.NodeVisitor):
    """Collect Thread constructions + every ``.join`` receiver and
    ``.daemon = True`` target, then reconcile."""

    def __init__(self, ctx):
        self.ctx = ctx
        self.births: list[_ThreadBirth] = []
        self.join_recvs: set[tuple] = set()   # (cls|None, recv text)
        self.daemon_sets: set[tuple] = set()
        self.cls_stack: list[str] = []
        self.fn_stack: list[str] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.cls_stack.append(node.name)
        self.generic_visit(node)
        self.cls_stack.pop()

    def _fn(self, node) -> None:
        self.fn_stack.append(node.name)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = _fn

    def _cls(self):
        return self.cls_stack[-1] if self.cls_stack else None

    def visit_Assign(self, node: ast.Assign) -> None:
        if (isinstance(node.value, ast.Call)
                and terminal_name(node.value.func) == "Thread"):
            b = _ThreadBirth(node.value,
                             unparse(node.targets[0]),
                             self._cls(),
                             self.fn_stack[-1] if self.fn_stack
                             else None)
            b.daemon = any(
                kw.arg == "daemon"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.value.keywords)
            self.births.append(b)
        for t in node.targets:
            # t.daemon = True after construction
            if (isinstance(t, ast.Attribute) and t.attr == "daemon"
                    and isinstance(node.value, ast.Constant)
                    and node.value.value is True):
                self.daemon_sets.add((self._cls(), unparse(t.value)))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if terminal_name(fn) == "Thread" and not any(
                b.node is node for b in self.births):
            # Unassigned construction (e.g. Thread(...).start(), or a
            # list comprehension element).
            b = _ThreadBirth(node, None, self._cls(),
                             self.fn_stack[-1] if self.fn_stack
                             else None)
            b.daemon = any(
                kw.arg == "daemon"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords)
            self.births.append(b)
        if (isinstance(fn, ast.Attribute) and fn.attr == "join"
                and not isinstance(fn.value, ast.Constant)):
            # Recorded with BOTH class and function scope: `self.X`
            # threads may be joined from any method (the close()
            # contract), but a local thread's join must be reachable
            # from its own function — a bare `t.join()` in some OTHER
            # method says nothing about this birth.
            self.join_recvs.add((self._cls(),
                                 self.fn_stack[-1] if self.fn_stack
                                 else None,
                                 unparse(fn.value)))
        self.generic_visit(node)


def _joined(w: _Pt015Walker, b: _ThreadBirth) -> bool:
    if b.target is None:
        return False
    if b.target.startswith("self."):
        # Attribute-held threads: a join anywhere in the class is the
        # close()/stop() path the rule asks for.
        return any(cls == b.cls and recv == b.target
                   for cls, fn, recv in w.join_recvs)
    # Locally-named threads: an exact-name join in the SAME function,
    # or (`threads.append(t)` + `for t in threads: t.join()`) any
    # bare-name join in the same function — a join in some OTHER
    # method does not reach this birth.
    return any(cls == b.cls and fn == b.fn
               and (recv == b.target or "." not in recv)
               for cls, fn, recv in w.join_recvs)


@rule("PT015",
      "thread-hygiene: non-daemon thread without a reachable join",
      applies=lambda ctx: ctx.in_pkg)
def check_pt015(ctx: FileContext) -> list[Finding]:
    w = _Pt015Walker(ctx)
    w.visit(ctx.tree)
    findings: list[Finding] = []
    for b in w.births:
        if b.daemon:
            continue
        if b.target is not None and (b.cls, b.target) in w.daemon_sets:
            continue
        if _joined(w, b):
            continue
        where = (f"self.{b.target.split('.', 1)[1]}"
                 if b.target and b.target.startswith("self.")
                 else (b.target or "<unassigned>"))
        findings.append(ctx.finding(
            b.node, "PT015",
            f"thread {where} is neither daemonized nor joined — a "
            f"zombie thread outlives its owner's close() and wakes "
            f"against torn-down state (the PR 2 server contract: "
            f"daemon=True, or a bounded join in a close/stop path)"))
    return findings
