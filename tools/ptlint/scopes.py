"""Shared scope/dataflow helpers — the core every pass rides.

The old checker's 12 visitors each re-derived the same three facts:
what name a call terminates in, what module an alias is bound to, and
what syntactic context (loop body, function, ``with``-held lock) the
node sits in. This module centralizes them so a new pass is mostly its
decision logic:

- :func:`terminal_name` — the last identifier of a receiver chain;
- :class:`ImportMap` — module aliases and from-import bindings, the
  dodge-proof way to recognize ``import time as _t`` / ``from
  jax.random import categorical as c``;
- :class:`ContextWalker` — a NodeVisitor base tracking the enclosing
  function stack, loop depth, and the stack of ``with``-held locks
  (any context-manager expression whose name looks lock-ish:
  ``self._lock``, ``r.lock``, ``self._cond`` ...);
- :func:`index_loads_stores` — per-function expression occurrence
  index (by ``ast.unparse`` string) for the read-after-donate and
  key-reuse dataflow passes.
"""

from __future__ import annotations

import ast

#: Attribute/variable names treated as locks for the concurrency
#: passes. Name-based on purpose: the repo's idiom is ``_lock`` /
#: ``_load_lock`` / ``_cond`` / ``lock`` — a lock you can't tell is a
#: lock from its name is already a review finding.
_LOCKISH_EXACT = frozenset({"mu", "_mu", "mutex", "_mutex"})


def is_lockish(name: str | None) -> bool:
    if not name:
        return False
    low = name.lower()
    return ("lock" in low or "cond" in low or low in _LOCKISH_EXACT)


def terminal_name(node: ast.expr) -> str | None:
    """Last identifier of a receiver expression: ``optimizer`` for
    ``self.optimizer``, ``join`` for ``t.join``, the func's terminal
    for a call."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Call):
        return terminal_name(node.func)
    return None


def unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover — malformed synthetic nodes
        return ""


class ImportMap(ast.NodeVisitor):
    """Module aliases + from-import bindings for one file.

    ``modules`` maps local name -> dotted module path (``_t`` ->
    ``time``, ``jr`` -> ``jax.random``); ``from_names`` maps local
    name -> (module, original name) for every from-import.
    """

    def __init__(self, tree: ast.AST):
        self.modules: dict[str, str] = {}
        self.from_names: dict[str, tuple[str, str]] = {}
        self.visit(tree)

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            local = a.asname or a.name.split(".")[0]
            # `import jax.random` binds `jax`; with an asname it binds
            # the full dotted module.
            self.modules[local] = a.name if a.asname else local

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if not node.module:
            return
        for a in node.names:
            if a.name == "*":
                continue
            self.from_names[a.asname or a.name] = (node.module, a.name)
            # `from jax import random` binds a module object too.
            self.modules.setdefault(a.asname or a.name,
                                    f"{node.module}.{a.name}")

    def module_aliases(self, dotted: str) -> set[str]:
        """Local names bound to module ``dotted`` (exact match)."""
        return {local for local, mod in self.modules.items()
                if mod == dotted}

    def from_bindings(self, module: str,
                      names: frozenset | set) -> dict[str, str]:
        """local name -> original name, for from-imports of ``names``
        out of ``module``."""
        return {local: orig
                for local, (mod, orig) in self.from_names.items()
                if mod == module and orig in names}


class HeldLock:
    """One ``with``-held lock: its expression text and terminal name."""

    __slots__ = ("expr", "name", "lineno")

    def __init__(self, expr: str, name: str, lineno: int):
        self.expr = expr
        self.name = name
        self.lineno = lineno


class ContextWalker(ast.NodeVisitor):
    """NodeVisitor tracking function stack, loop depth, and the stack
    of with-held locks. Subclasses override ``handle_call`` (and
    anything else) and read ``self.fn_stack`` / ``self.loop_depth`` /
    ``self.held_locks``."""

    def __init__(self):
        self.fn_stack: list[str] = []
        self.loop_depth = 0
        self.held_locks: list[HeldLock] = []

    # -- functions

    def _fn(self, node) -> None:
        self.fn_stack.append(node.name)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = _fn

    # -- loops

    def _loop(self, node) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_AsyncFor = visit_While = _loop

    # -- with-held locks

    def _with(self, node) -> None:
        pushed = 0
        for item in node.items:
            expr = item.context_expr
            # `with self._lock:` — a bare lock expression; a call like
            # `with chaos.armed(plan):` is a context manager, not a
            # lock acquisition.
            if isinstance(expr, (ast.Name, ast.Attribute)):
                name = terminal_name(expr)
                if is_lockish(name):
                    self.held_locks.append(
                        HeldLock(unparse(expr), name or "",
                                 expr.lineno))
                    pushed += 1
        self.generic_visit(node)
        for _ in range(pushed):
            self.held_locks.pop()

    visit_With = visit_AsyncWith = _with

    def holding(self) -> bool:
        return bool(self.held_locks)

    def holds_expr(self, expr: str) -> bool:
        return any(h.expr == expr for h in self.held_locks)


def _store_targets(node: ast.AST):
    """Expression nodes bound by an assignment-ish statement."""
    if isinstance(node, ast.Assign):
        return list(node.targets)
    if isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.NamedExpr)):
        return [node.target]
    if isinstance(node, (ast.For, ast.AsyncFor)):
        return [node.target]
    return []


def _flatten_targets(targets):
    out = []
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            out.extend(_flatten_targets(t.elts))
        else:
            out.append(t)
    return out


def index_loads_stores(fn: ast.AST) -> tuple[dict, dict]:
    """(loads, stores): expression text -> sorted line numbers, over
    one function body. Loads cover Name/Attribute/Subscript in Load
    context; stores cover assignment/loop/with-as targets and
    ``del``. Nested function bodies are included (closures read the
    same frame)."""
    loads: dict[str, list[int]] = {}
    stores: dict[str, list[int]] = {}
    for node in ast.walk(fn):
        if isinstance(node, (ast.Name, ast.Attribute, ast.Subscript)):
            if isinstance(getattr(node, "ctx", None), ast.Load):
                loads.setdefault(unparse(node), []).append(node.lineno)
            elif isinstance(getattr(node, "ctx", None),
                            (ast.Store, ast.Del)):
                stores.setdefault(unparse(node), []).append(node.lineno)
        for t in _flatten_targets(_store_targets(node)):
            stores.setdefault(unparse(t), []).append(t.lineno)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    for t in _flatten_targets([item.optional_vars]):
                        stores.setdefault(unparse(t), []).append(
                            t.lineno)
    for d in (loads, stores):
        for k in d:
            d[k] = sorted(set(d[k]))
    return loads, stores
