"""PT018/PT019/PT020 — the dispatch-discipline passes.

The perf work the tree banks on (fused spec windows, overlapped
bucketed collectives, the steady-state decode step that re-uploads
nothing) is only as good as the *compiled programs staying compiled*:
a stray host sync serializes async dispatch, a silent retrace turns a
hot loop into a compile loop, and an f64 upcast doubles wire and HBM
bytes without failing a single test. These passes police the three
classes statically; :mod:`ptype_tpu.jitwatch` is the runtime half and
:mod:`ptype_tpu.progaudit` the program-level contract.

- **PT018 host-sync-in-hot-path**: ``.item()``, ``jax.device_get``,
  and ``np.asarray``/``np.array``/``float()``/``int()`` of a
  DEVICE-POSITIVE value inside a LOOP body in the hot modules
  (``serve_engine/``, ``train/``, ``models/``, ``parallel/``) — each
  one blocks the host on the device stream, once per iteration.
  Device-positive means the pass PROVED the value came off a device:
  assigned from a ``jnp.*``/``jax.*``/``lax.*`` call or from a call
  through a ``jax.jit`` binding, in this file. Host mirrors (the
  engine's ``np.zeros`` slot state, ``nxt_host = np.array(nxt)``)
  never flag — the false-positive-free charter. Sanctioned seams:
  meter/telemetry/probe functions (``Info``/``summary``/
  ``measure_*``/``check_*`` and friends), where a sync is the point.

- **PT019 retrace-hazard**: ``jax.jit`` applied to a ``lambda`` or a
  locally-defined closure inside a per-call method, ``jax.jit``
  constructed inside a loop outside the init/builder seams, or the
  construct-and-call form ``jax.jit(f)(x)`` — every pass builds a
  FRESH function object, so jit's cache re-keys and the program
  RE-TRACES per call. The house idiom caches the jitted callable at
  ``__init__``/module scope or in a ``_build*``/``_make*``/``*_prog``
  helper memoized by the caller; one-shot probe seams
  (``measure_*``, ``bench*``) are exempt — their jit runs once by
  charter.

- **PT020 f64-drift**: ``np.float64`` (call, dtype arg, or
  ``.astype``), and dtype-less ``np.array``/``np.asarray`` of float
  literals or dtype-less ``np.zeros``/``ones``/``full``/``empty`` in
  device-adjacent dirs (``parallel/``, ``serve_engine/``,
  ``models/``, ``train/``) — numpy defaults to float64, and an f64
  leaf flowing into device code either upcasts the program (2x HBM +
  wire bytes) or trips the x64 guard at the worst possible time.
  A positional or keyword dtype of any kind satisfies the rule (the
  house idiom is ``np.zeros(n, np.int32)``); int-literal content is
  exempt (int64 host indexes are normal bookkeeping).
"""

from __future__ import annotations

import ast

from .core import FileContext, Finding, rule
from .scopes import ContextWalker, ImportMap, unparse

#: The hot modules: dirs (and top-level files) whose loops dispatch
#: device programs.
_HOT_DIRS = ("serve_engine", "train", "models", "parallel")
_HOT_FILES = ("serve.py",)

#: Function-name shapes that ARE the sanctioned host-sync / one-shot
#: probe seams: telemetry, meters, summaries, audits, benches — a
#: sync (or a throwaway jit) there is the contract, not a leak.
_SANCTIONED_PREFIXES = (
    "info", "summary", "snapshot", "measure", "check", "audit",
    "render", "bench", "export", "stats", "dump", "describe",
)
_SANCTIONED_EXACT = frozenset({
    "Info", "__repr__", "__str__", "close",
})

#: Module paths whose calls produce device values.
_DEVICE_MODULES = frozenset({
    "jax", "jax.numpy", "jax.lax", "jax.random", "jax.nn",
})


def _in_hot_dir(ctx: FileContext) -> bool:
    return ctx.in_pkg and (any(ctx.in_dir(d) for d in _HOT_DIRS)
                           or ctx.basename in _HOT_FILES)


def _is_sanctioned_fn(fn_stack: list[str]) -> bool:
    for name in fn_stack:
        if name in _SANCTIONED_EXACT:
            return True
        low = name.lstrip("_").lower()
        if low.startswith(_SANCTIONED_PREFIXES):
            return True
    return False


class _JaxNames:
    """Shared alias resolution for the jax/numpy module universe."""

    def __init__(self, tree: ast.AST):
        self.imports = ImportMap(tree)
        self.np_mods = (self.imports.module_aliases("numpy")
                        or {"np", "numpy"})
        self.jax_mods = self.imports.module_aliases("jax") or {"jax"}
        self.device_mods: set[str] = set()
        for dotted in _DEVICE_MODULES:
            self.device_mods |= self.imports.module_aliases(dotted)
        self.device_mods |= self.jax_mods
        self.from_jit = {
            local for local, (mod, orig)
            in self.imports.from_names.items()
            if mod == "jax" and orig == "jit"}
        self.from_device_get = {
            local for local, (mod, orig)
            in self.imports.from_names.items()
            if mod == "jax" and orig == "device_get"}

    def root_module(self, fn: ast.expr) -> str | None:
        """The module alias a call chain roots at: ``jnp`` for
        ``jnp.where(...)``, ``jax`` for ``jax.random.split(...)``."""
        node = fn
        while isinstance(node, ast.Attribute):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    def is_device_call(self, node: ast.expr) -> bool:
        if not isinstance(node, ast.Call):
            return False
        root = self.root_module(node.func)
        return root is not None and root in self.device_mods

    def is_jit_call(self, node: ast.Call) -> bool:
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr == "jit"
                and isinstance(fn.value, ast.Name)
                and fn.value.id in self.jax_mods):
            return True
        return isinstance(fn, ast.Name) and fn.id in self.from_jit


def _jit_bindings(tree: ast.AST, names: _JaxNames) -> set[str]:
    """Expression texts bound to a ``jax.jit(...)`` product anywhere
    in the file (``self._step = jax.jit(...)``, ``fn = jit(...)``) —
    calls THROUGH these produce device values."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and names.is_jit_call(node.value):
            for t in node.targets:
                out.add(unparse(t))
    return out


def _device_names(fn: ast.AST, names: _JaxNames,
                  jit_bound: set[str]) -> set[str]:
    """Names/attribute texts PROVEN device-resident inside ``fn``:
    assigned from a jnp/jax/lax call or a call through a jit
    binding. File-local, positive evidence only."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        is_dev = (names.is_device_call(v)
                  or (isinstance(v, ast.Call)
                      and unparse(v.func) in jit_bound)
                  or (isinstance(v, ast.Tuple)
                      and any(names.is_device_call(e)
                              for e in v.elts)))
        if not is_dev:
            continue
        for t in node.targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    out.add(unparse(e))
            else:
                out.add(unparse(t))
    return out


# --------------------------------------------------------------- PT018


class _Pt018Walker(ContextWalker):
    """Flag host-sync verbs inside loop bodies of hot modules."""

    def __init__(self, ctx: FileContext, findings: list[Finding]):
        super().__init__()
        self.ctx = ctx
        self.findings = findings
        self.names = _JaxNames(ctx.tree)
        self.jit_bound = _jit_bindings(ctx.tree, self.names)
        #: Per-function device-positive name sets (stack).
        self.dev_names: list[set] = []

    def _fn(self, node) -> None:
        self.dev_names.append(
            _device_names(node, self.names, self.jit_bound))
        super()._fn(node)
        self.dev_names.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = _fn

    def _device_positive(self, node: ast.expr) -> bool:
        """True when ``node`` provably came off a device: a
        device-call expression, a name assigned from one, or a
        subscript/attr whose base did."""
        if self.names.is_device_call(node):
            return True
        if (isinstance(node, ast.Call)
                and unparse(node.func) in self.jit_bound):
            return True
        base = node
        while isinstance(base, ast.Subscript):
            base = base.value
        text = unparse(base)
        return any(text in s for s in self.dev_names)

    def _flag(self, node, what: str, hint: str) -> None:
        self.findings.append(self.ctx.finding(
            node, "PT018",
            f"{what} inside a hot-path loop — a device-to-host sync "
            f"per iteration serializes async dispatch (the "
            f"three-dispatch spec window measured 0.77x before its "
            f"syncs were fused out); {hint}"))

    def _np_verb(self, fn: ast.expr, verbs: tuple) -> str | None:
        if (isinstance(fn, ast.Attribute) and fn.attr in verbs
                and isinstance(fn.value, ast.Name)
                and fn.value.id in self.names.np_mods):
            return fn.attr
        if isinstance(fn, ast.Name):
            src = self.names.imports.from_names.get(fn.id)
            if src is not None and src[0] == "numpy" \
                    and src[1] in verbs:
                return src[1]
        return None

    def visit_Call(self, node: ast.Call) -> None:
        if not self.loop_depth or not self.fn_stack \
                or _is_sanctioned_fn(self.fn_stack):
            self.generic_visit(node)
            return
        fn = node.func
        # x.item() — the canonical one-scalar-per-iteration sync.
        if isinstance(fn, ast.Attribute) and fn.attr == "item" \
                and not node.args:
            self._flag(node, f"{unparse(fn)}()",
                       "batch the host read: one np.asarray of the "
                       "whole result after the loop")
        # jax.device_get(...) — explicit transfer per iteration.
        elif ((isinstance(fn, ast.Attribute)
               and fn.attr == "device_get"
               and isinstance(fn.value, ast.Name)
               and fn.value.id in self.names.jax_mods)
              or (isinstance(fn, ast.Name)
                  and fn.id in self.names.from_device_get)):
            self._flag(node, "jax.device_get(...)",
                       "hoist one device_get of the stacked result "
                       "out of the loop")
        else:
            # np.asarray/np.array of a PROVEN device value — implicit
            # d2h per iteration. Host mirrors and literals never flag.
            verb = self._np_verb(fn, ("asarray", "array"))
            if verb is not None and node.args \
                    and self._device_positive(node.args[0]):
                self._flag(node, f"np.{verb}({unparse(node.args[0])})",
                           "pull the whole batch once outside the "
                           "loop, or keep the value on device")
            # float(x[i]) / int(x[i]) on a device value — element-wise
            # host reads.
            elif (isinstance(fn, ast.Name)
                  and fn.id in ("float", "int") and node.args
                  and isinstance(node.args[0], (ast.Subscript,
                                                ast.Call))
                  and self._device_positive(node.args[0])):
                self._flag(node, f"{fn.id}({unparse(node.args[0])})",
                           "read the array once (np.asarray outside "
                           "the loop) and index the host copy")
        self.generic_visit(node)


@rule("PT018", "host sync inside a hot-path loop",
      applies=_in_hot_dir)
def check_pt018(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _Pt018Walker(ctx, findings).visit(ctx.tree)
    return findings


# --------------------------------------------------------------- PT019

#: Function-name shapes sanctioned to CONSTRUCT jits: builders the
#: caller memoizes (the `_chunk_prog` idiom) and init paths.
_PT019_BUILDER_PREFIXES = ("_build", "_make", "build_", "make_",
                           "init", "_init", "_compile", "compile_")
_PT019_BUILDER_SUFFIXES = ("_prog", "_fn", "_program", "_step_fn")


def _is_builder(name: str) -> bool:
    return (name.startswith(_PT019_BUILDER_PREFIXES)
            or name.endswith(_PT019_BUILDER_SUFFIXES)
            or name in ("__init__", "__new__"))


class _Pt019Walker(ContextWalker):
    def __init__(self, ctx: FileContext, findings: list[Finding]):
        super().__init__()
        self.ctx = ctx
        self.findings = findings
        self.names = _JaxNames(ctx.tree)
        #: Names of functions DEFINED inside the currently-walked
        #: function body (stack of sets) — jitting one of these from
        #: a sibling statement builds a fresh callee per call.
        self.local_defs: list[set] = []
        #: Inner jit-call nodes already flagged as part of an outer
        #: construct-and-call expression — ONE defect, one finding.
        self._covered: set[int] = set()

    def _fn(self, node) -> None:
        if self.local_defs:
            self.local_defs[-1].add(node.name)
        self.local_defs.append(set())
        super()._fn(node)
        self.local_defs.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = _fn

    def _sanctioned(self) -> bool:
        return (any(_is_builder(n) for n in self.fn_stack)
                or _is_sanctioned_fn(self.fn_stack))

    def _flag(self, node, shape: str) -> None:
        self.findings.append(self.ctx.finding(
            node, "PT019",
            f"jax.jit {shape} — the wrapped function object is fresh "
            f"every pass, so jit's cache re-keys and the program "
            f"RE-TRACES per call (a silent compile loop; jitwatch's "
            f"recompile-storm pages on exactly this at runtime); "
            f"cache the jitted callable at __init__/module scope or "
            f"in a memoized *_prog builder"))

    def visit_Call(self, node: ast.Call) -> None:
        # jax.jit(f)(x): construct-and-call — never cached anywhere.
        # Checked FIRST and the inner jit call marked covered, so the
        # one expression yields one finding, not a second from the
        # lambda/closure branch below.
        if (isinstance(node.func, ast.Call)
                and self.names.is_jit_call(node.func) and self.fn_stack
                and not self._sanctioned()):
            self._flag(node, "constructed and called in one "
                             "expression (jax.jit(f)(...))")
            self._covered.add(id(node.func))
        if self.names.is_jit_call(node) and self.fn_stack \
                and id(node) not in self._covered \
                and not self._sanctioned():
            target = node.args[0] if node.args else None
            if self.loop_depth:
                self._flag(node, "constructed inside a loop")
            elif isinstance(target, ast.Lambda):
                self._flag(node, "of a lambda in a per-call method")
            elif (isinstance(target, ast.Name) and self.local_defs
                  and target.id in self.local_defs[-1]):
                self._flag(node, f"of locally-defined closure "
                                 f"'{target.id}' in a per-call method")
        self.generic_visit(node)


@rule("PT019", "per-call jax.jit construction re-keys the trace cache",
      applies=lambda ctx: ctx.in_pkg)
def check_pt019(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _Pt019Walker(ctx, findings).visit(ctx.tree)
    return findings


# --------------------------------------------------------------- PT020

_F64_NAMES = frozenset({"float64", "double"})
#: Positional index of the dtype parameter per constructor.
_CTOR_DTYPE_POS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2,
                   "array": 1, "asarray": 1}


def _has_float_literal(node: ast.expr) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value,
                                                        float):
            return True
    return False


class _Pt020Walker(ContextWalker):
    def __init__(self, ctx: FileContext, findings: list[Finding]):
        super().__init__()
        self.ctx = ctx
        self.findings = findings
        self.names = _JaxNames(ctx.tree)

    def _flag(self, node, what: str, hint: str) -> None:
        self.findings.append(self.ctx.finding(
            node, "PT020",
            f"{what} in a device-adjacent module — numpy defaults to "
            f"float64, and an f64 leaf reaching device code either "
            f"upcasts the whole program (2x HBM + wire bytes) or "
            f"trips the jax x64 guard; {hint}"))

    def _np_attr(self, fn: ast.expr) -> str | None:
        if (isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id in self.names.np_mods):
            return fn.attr
        return None

    def _is_f64_dtype(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Constant):
            return node.value in ("float64", "double")
        if isinstance(node, ast.Attribute):
            return (node.attr in _F64_NAMES
                    and isinstance(node.value, ast.Name)
                    and node.value.id in self.names.np_mods)
        if isinstance(node, ast.Name):
            src = self.names.imports.from_names.get(node.id)
            return (src is not None and src[0] == "numpy"
                    and src[1] in _F64_NAMES)
        return False

    def _dtype_arg(self, node: ast.Call, attr: str) -> ast.expr | None:
        """The dtype argument of a numpy constructor call, positional
        (``np.zeros(n, np.int32)`` — the house idiom) or keyword."""
        for kw in node.keywords:
            if kw.arg == "dtype":
                return kw.value
        pos = _CTOR_DTYPE_POS.get(attr)
        if pos is not None and len(node.args) > pos:
            return node.args[pos]
        return None

    def visit_Call(self, node: ast.Call) -> None:
        attr = self._np_attr(node.func)
        # Explicit f64: np.float64(x), dtype=np.float64/"float64",
        # .astype(np.float64).
        if attr in _F64_NAMES:
            self._flag(node, f"np.{attr}(...)",
                       "use np.float32 (or the config dtype)")
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "astype" and node.args
              and self._is_f64_dtype(node.args[0])):
            self._flag(node, f".astype({unparse(node.args[0])})",
                       "cast to float32 (or the config dtype)")
        elif attr in _CTOR_DTYPE_POS:
            dtype = self._dtype_arg(node, attr)
            if dtype is not None and self._is_f64_dtype(dtype):
                self._flag(node, f"dtype {unparse(dtype)}",
                           "name a 32-bit (or config) dtype")
            elif dtype is None and attr in ("array", "asarray"):
                # Dtype-less literal construction drifts only when
                # float content is involved (int64 host indexes are
                # the normal bookkeeping idiom).
                if node.args and _has_float_literal(node.args[0]):
                    self._flag(
                        node,
                        f"dtype-less np.{attr} of float literals",
                        "write dtype=np.float32 — the literal "
                        "defaults to f64")
            elif dtype is None:
                self._flag(node, f"dtype-less np.{attr}(...)",
                           "name the dtype — np." + attr
                           + " defaults to float64")
        self.generic_visit(node)


@rule("PT020", "float64 drift into device-adjacent numpy",
      applies=_in_hot_dir)
def check_pt020(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _Pt020Walker(ctx, findings).visit(ctx.tree)
    return findings
