"""ptlint — the repo's stdlib-only static analyzer, as a package.

The reference gated every commit on golangci-lint
(/root/reference/.golangci.yml) and leaned on Go's race detector to
keep its concurrency honest; this image bakes in no Python linter and
installs are barred, so ``make lint`` runs this checker instead. v2
grows the old single-file walker (tools/lint.py, 12 ad-hoc visitors)
into a package with a shared scope/dataflow core and a rule registry:

- ``core``       — Finding / FileContext / registry / suppressions
                   (``# ptlint: disable=PTxxx`` with justification,
                   unused-suppression detection), JSON output
- ``scopes``     — the shared dataflow helpers every pass rides:
                   lock-context walking, import-alias resolution,
                   terminal names, per-function load/store indexes
- ``rules_style``  — the pyflakes-grade base checks (E999/E722/B006/
                     E711/F541/F401/F821)
- ``rules_domain`` — PT001–PT012 (migrated from tools/lint.py with
                     behavior pinned by a golden-output test) plus
                     PT021 KV-wire-serialization single-home and
                     PT022–PT024 (ZeRO-3 residency, axis-name, and
                     loadgen seeded-RNG single-home)
- ``rules_concurrency`` — PT013 lock-discipline, PT014
                     blocking-under-lock, PT015 thread-hygiene
- ``rules_jax``  — PT016 donation-safety, PT017 RNG-key-reuse
- ``rules_dispatch`` — PT018 host-sync-in-hot-path, PT019
                     retrace-hazard, PT020 f64-drift (the static half
                     of the dispatch-discipline plane; jitwatch.py is
                     the runtime half, progaudit.py the program
                     contract)

The rule catalogue (ID, rationale, example, suppression policy) lives
in docs/LINTING.md. Exit 0 when clean; 1 with one
``path:line: code message`` per finding (or a JSON array under
``--json``).
"""

from __future__ import annotations

from .core import (  # noqa: F401 — the package surface
    FileContext,
    Finding,
    RULES,
    check_file,
    check_file_findings,
    iter_py,
    main,
    run_paths,
)

# Importing the rule modules registers every rule with the registry.
from . import rules_style  # noqa: F401,E402
from . import rules_domain  # noqa: F401,E402
from . import rules_concurrency  # noqa: F401,E402
from . import rules_jax  # noqa: F401,E402
from . import rules_dispatch  # noqa: F401,E402
