"""PT001–PT012 (plus PT021–PT024): the house rules.

PT001–PT012 were migrated from tools/lint.py; each rule guards one
architectural seam this repo earned the hard way (the full rationale
per rule lives in docs/LINTING.md). Migration is behavior-preserving:
the golden-output test in tests/test_ptlint.py pins these against the
old walker's findings on a fixture tree. PT021 (KV wire serialization
outside the migration home, ISSUE 16) joins them here because it is
the same single-home family as PT008/PT011; PT022 (full-tree param
allgather in ``train/``, ISSUE 17) extends that family to the ZeRO-3
residency contract; PT023 (hard-coded flat ``"data"`` axis names
outside ``parallel/``, ISSUE 18) extends it to the topology plane's
axis-name discipline; PT024 (raw ``random.*``/``np.random.*`` draws
in ``loadgen/`` outside the seeded RNG home, ISSUE 19) extends it to
the traffic plane's replay discipline.
"""

from __future__ import annotations

import ast

from .core import FileContext, Finding, rule
from .scopes import ContextWalker, terminal_name

# --------------------------------------------------------------- PT001

#: Method/function names that dispatch one eager collective per call.
_EAGER_COLLECTIVES = frozenset({
    "push", "push_scatter", "all_reduce", "all_gather",
    "reduce_scatter", "quantized_all_reduce",
    "quantized_reduce_scatter", "all_to_all", "ring_shift",
})


class _PerLeafCollectiveCheck(ast.NodeVisitor):
    def __init__(self, ctx, findings):
        self.ctx = ctx
        self.findings = findings
        self.loop_depth = 0

    def _loop(self, node) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_AsyncFor = visit_While = _loop
    visit_ListComp = visit_SetComp = _loop
    visit_DictComp = visit_GeneratorExp = _loop

    def visit_Call(self, node: ast.Call) -> None:
        name = terminal_name(node.func)
        if self.loop_depth and name in _EAGER_COLLECTIVES:
            self.findings.append(self.ctx.finding(
                node, "PT001",
                f"eager collective {name!r} called in a per-leaf "
                f"loop; bucket it (TensorStore.push_tree / "
                f"collectives.tree_all_reduce)"))
        self.generic_visit(node)


@rule("PT001", "eager collective in a per-leaf loop (train/ only)",
      applies=lambda ctx: ctx.in_dir("train"))
def check_pt001(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _PerLeafCollectiveCheck(ctx, findings).visit(ctx.tree)
    return findings


# --------------------------------------------------------------- PT002


class _SleepInLoopCheck(ast.NodeVisitor):
    def __init__(self, ctx, findings):
        self.ctx = ctx
        self.findings = findings
        self.loop_depth = 0

    def _loop(self, node) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_AsyncFor = visit_While = _loop

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (self.loop_depth
                and isinstance(fn, ast.Attribute) and fn.attr == "sleep"
                and isinstance(fn.value, ast.Name)
                and fn.value.id in ("time", "_time")):
            self.findings.append(self.ctx.finding(
                node, "PT002",
                "bare time.sleep in a loop; use ptype_tpu.retry."
                "Backoff (jittered, capped) or an Event.wait deadline"))
        self.generic_visit(node)


@rule("PT002", "bare time.sleep in a loop (retry.py is the sleeper)",
      applies=lambda ctx: ctx.in_pkg and ctx.basename != "retry.py")
def check_pt002(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _SleepInLoopCheck(ctx, findings).visit(ctx.tree)
    return findings


# --------------------------------------------------------------- PT003

_GATED_SERVICES = frozenset({"llm"})


@rule("PT003", "direct new_client('llm') bypasses the gateway",
      applies=lambda ctx: ctx.in_pkg and not ctx.in_dir("gateway"))
def check_pt003(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = terminal_name(node.func)
        if (name == "new_client" and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value in _GATED_SERVICES):
            findings.append(ctx.finding(
                node, "PT003",
                f"direct new_client({node.args[0].value!r}) bypasses "
                f"the inference gateway (admission control, shedding, "
                f"load-aware routing); use gateway.InferenceGateway "
                f"or a GatewayActor service"))
    return findings


# --------------------------------------------------------------- PT004


@rule("PT004", "bare print() in framework code",
      applies=lambda ctx: ctx.in_pkg and ctx.basename != "__main__.py")
def check_pt004(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            findings.append(ctx.finding(
                node, "PT004",
                "bare print() in framework code; use logs.get_logger "
                "(trace-correlated kv logging) or a trace span event"))
    return findings


# --------------------------------------------------------------- PT005

_METRIC_FAMILIES = frozenset({"Counter", "Timing", "Gauge", "Histogram"})
_METRICS_ALIASES = frozenset({"metrics", "metrics_mod"})


@rule("PT005", "metric family constructed outside MetricsRegistry",
      applies=lambda ctx: ctx.in_pkg and ctx.basename != "metrics.py")
def check_pt005(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = None
        if isinstance(fn, ast.Name) and fn.id in _METRIC_FAMILIES:
            name = fn.id
        elif (isinstance(fn, ast.Attribute)
              and fn.attr in _METRIC_FAMILIES
              and isinstance(fn.value, ast.Name)
              and fn.value.id in _METRICS_ALIASES):
            name = fn.attr
        if name is not None:
            findings.append(ctx.finding(
                node, "PT005",
                f"direct {name}() construction bypasses the "
                f"MetricsRegistry — the health sampler can't see it "
                f"(no series, no alerts); use "
                f"registry.{name.lower()}(name)"))
    return findings


# --------------------------------------------------------------- PT006

_QUANT_HELPER_PREFIXES = ("_q_", "quantize", "dequantize")


def _is_int8_arg(node: ast.expr) -> bool:
    if isinstance(node, ast.Constant):
        return node.value == "int8"
    if isinstance(node, ast.Attribute) and node.attr == "int8":
        return True
    if (isinstance(node, ast.Call) and node.args
            and isinstance(node.args[0], ast.Constant)):
        return node.args[0].value == "int8"
    return False


class _RawInt8CastCheck(ContextWalker):
    def __init__(self, ctx, findings):
        super().__init__()
        self.ctx = ctx
        self.findings = findings

    def _sanctioned(self) -> bool:
        return any(name.startswith(_QUANT_HELPER_PREFIXES)
                   for name in self.fn_stack)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        dtype_args = list(node.args[:1]) + [
            kw.value for kw in node.keywords if kw.arg == "dtype"]
        if (isinstance(fn, ast.Attribute) and fn.attr == "astype"
                and any(_is_int8_arg(a) for a in dtype_args)
                and not self._sanctioned()):
            self.findings.append(self.ctx.finding(
                node, "PT006",
                "raw .astype(int8) narrowing outside the quantize "
                "helpers — an unscaled int8 cast destroys gradients "
                "(saturation + underflow); use collectives."
                "_q_int8_blockwise / quantize_leaf, which carry "
                "per-block absmax scales"))
        self.generic_visit(node)


@rule("PT006", "raw int8 cast outside the quantize helpers",
      applies=lambda ctx: ctx.in_pkg and ctx.in_dir("parallel"))
def check_pt006(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _RawInt8CastCheck(ctx, findings).visit(ctx.tree)
    return findings


# --------------------------------------------------------------- PT007

_OPT_INIT_SANCTIONED = ("__init__", "init_", "_init")


class _FullTreeOptStateCheck(ContextWalker):
    def __init__(self, ctx, findings):
        super().__init__()
        self.ctx = ctx
        self.findings = findings

    def _sanctioned(self) -> bool:
        return any(name.startswith(_OPT_INIT_SANCTIONED)
                   for name in self.fn_stack)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr == "init"
                and not self._sanctioned()):
            recv = terminal_name(fn.value)
            if recv is not None and (
                    "optimizer" in recv.lower()
                    or recv in ("opt", "_opt")):
                self.findings.append(self.ctx.finding(
                    node, "PT007",
                    f"full-tree optimizer state constructed outside "
                    f"the init helpers ({recv}.init) — replicated "
                    f"moments cap trainable model size; hot paths "
                    f"must use the sharded state (parallel/zero."
                    f"ZeroState, 1/N per replica) or the per-bucket "
                    f"states the init helpers set up"))
        self.generic_visit(node)


@rule("PT007", "full-tree optimizer.init outside init helpers",
      applies=lambda ctx: ctx.in_dir("train"))
def check_pt007(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _FullTreeOptStateCheck(ctx, findings).visit(ctx.tree)
    return findings


# --------------------------------------------------------------- PT008


class _RawProfilerTraceCheck(ast.NodeVisitor):
    _VERBS = frozenset({"start_trace", "stop_trace"})

    def __init__(self, ctx, findings):
        self.ctx = ctx
        self.findings = findings
        self.from_profiler: set[str] = set()

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module and node.module.endswith("profiler"):
            for a in node.names:
                if a.name in self._VERBS:
                    self.from_profiler.add(a.asname or a.name)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        hit = None
        if (isinstance(fn, ast.Attribute) and fn.attr in self._VERBS
                and isinstance(fn.value, ast.Attribute)
                and fn.value.attr == "profiler"):
            hit = fn.attr            # jax.profiler.start_trace(...)
        elif (isinstance(fn, ast.Attribute) and fn.attr in self._VERBS
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "profiler"):
            hit = fn.attr            # from jax import profiler
        elif (isinstance(fn, ast.Name)
                and fn.id in self.from_profiler):
            hit = fn.id              # from jax.profiler import ...
        if hit is not None:
            self.findings.append(self.ctx.finding(
                node, "PT008",
                f"raw jax.profiler.{hit} — the profiler is "
                f"process-global and this call races the managed "
                f"capture plane; go through health/profiling.py "
                f"(start/stop/capture or the ptype.Profile endpoint)"))
        self.generic_visit(node)


@rule("PT008", "raw jax.profiler start/stop outside the managed seam",
      applies=lambda ctx: ctx.in_pkg and ctx.basename not in (
          "metrics.py", "profiling.py"))
def check_pt008(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _RawProfilerTraceCheck(ctx, findings).visit(ctx.tree)
    return findings


# --------------------------------------------------------------- PT009


@rule("PT009", "raw init_cache bank outside serve_engine/models",
      applies=lambda ctx: (ctx.in_pkg
                           and not ctx.in_dir("serve_engine")
                           and not ctx.in_dir("models")))
def check_pt009(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and terminal_name(node.func) == "init_cache"):
            findings.append(ctx.finding(
                node, "PT009",
                "raw init_cache full-reach bank allocation in "
                "serving code — resident KV must come from the paged "
                "block pool (serve_engine.BlockPool: ref-counted "
                "blocks, prefix reuse, LRU eviction), not a "
                "contiguous n_slots×reach bank"))
    return findings


# --------------------------------------------------------------- PT010


class _RawTimerCheck(ast.NodeVisitor):
    _VERBS = frozenset({"perf_counter", "time"})

    def __init__(self, ctx, findings):
        self.ctx = ctx
        self.findings = findings
        self.mods: set[str] = set()
        self.funcs: dict[str, str] = {}

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.name == "time":
                self.mods.add(a.asname or "time")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "time":
            for a in node.names:
                if a.name in self._VERBS:
                    self.funcs[a.asname or a.name] = a.name
        self.generic_visit(node)

    def _flag(self, node: ast.Call, verb: str) -> None:
        self.findings.append(self.ctx.finding(
            node, "PT010",
            f"raw time.{verb} in serve_engine/ — engine latency "
            f"stamps must ride the serving ledger's seams "
            f"(health/serving.py: enqueued/head_refused/admitted/"
            f"chunk/first_token/tokens_emitted/iteration/retired), "
            f"the one timing home the histograms, span tree, and "
            f"seam-cost probe all derive from"))

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr in self._VERBS
                and isinstance(fn.value, ast.Name)
                and fn.value.id in (self.mods or {"time", "_time"})):
            self._flag(node, fn.attr)
        elif isinstance(fn, ast.Name) and fn.id in self.funcs:
            self._flag(node, self.funcs[fn.id])
        self.generic_visit(node)


@rule("PT010", "raw wall-clock reads beside the serving ledger",
      applies=lambda ctx: ctx.in_pkg and ctx.in_dir("serve_engine"))
def check_pt010(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _RawTimerCheck(ctx, findings).visit(ctx.tree)
    return findings


# --------------------------------------------------------------- PT011


class _RawSamplingCheck(ast.NodeVisitor):
    _VERBS = frozenset({"categorical", "gumbel"})

    def __init__(self, ctx, findings):
        self.ctx = ctx
        self.findings = findings
        self.rand_mods: set[str] = set()
        self.funcs: dict[str, str] = {}

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.name == "jax.random" and a.asname:
                self.rand_mods.add(a.asname)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "jax":
            for a in node.names:
                if a.name == "random":
                    self.rand_mods.add(a.asname or "random")
        elif node.module == "jax.random":
            for a in node.names:
                if a.name in self._VERBS:
                    self.funcs[a.asname or a.name] = a.name
        self.generic_visit(node)

    def _flag(self, node: ast.Call, verb: str) -> None:
        self.findings.append(self.ctx.finding(
            node, "PT011",
            f"direct jax.random.{verb} sampling in serve_engine/ — "
            f"acceptance sampling has one RNG home (models/generate."
            f"py: sample_token_rows/draft_propose_paged/"
            f"spec_accept_rows, the contract-tested helpers); a raw "
            f"draw here silently rots the exact-distribution "
            f"contract"))

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in self._VERBS:
            base = fn.value
            if (isinstance(base, ast.Attribute)
                    and base.attr == "random"
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "jax"):
                self._flag(node, fn.attr)   # jax.random.categorical
            elif (isinstance(base, ast.Name)
                    and base.id in self.rand_mods):
                self._flag(node, fn.attr)   # random.categorical / jr.
        elif isinstance(fn, ast.Name) and fn.id in self.funcs:
            self._flag(node, self.funcs[fn.id])
        self.generic_visit(node)


@rule("PT011", "ad-hoc sampling draw beside the RNG home",
      applies=lambda ctx: ctx.in_pkg and ctx.in_dir("serve_engine"))
def check_pt011(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _RawSamplingCheck(ctx, findings).visit(ctx.tree)
    return findings


# --------------------------------------------------------------- PT012


@rule("PT012", "ActorServer built outside the replica-lifecycle home",
      applies=lambda ctx: (ctx.in_pkg
                           and not ctx.in_dir("reconciler")
                           and ctx.basename != "serve.py"))
def check_pt012(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and terminal_name(node.func) == "ActorServer"):
            findings.append(ctx.finding(
                node, "PT012",
                "direct ActorServer construction outside the "
                "replica-lifecycle home — the elastic reconciler can "
                "neither drain nor replace a replica it didn't "
                "build; construct through reconciler.replica."
                "serve_actor / ReplicaHost"))
    return findings


# --------------------------------------------------------------- PT021


class _KVWireCheck(ast.NodeVisitor):
    """KV wire serialization outside the migration home.

    ``quantize_leaf``/``dequantize_leaf`` are the int8+EF codec's only
    entry points; in ``serve_engine/`` they may appear in exactly ONE
    module — ``migrate.py``, the wire between serving classes. A
    second call site forks the wire format: its residual store and the
    migrator's drift apart, and the error-feedback contract (repeated
    transfers of the same block don't accumulate bias) silently
    breaks. Same single-home discipline PT008 applies to collectives
    and PT011 to sampling. Catches the direct call, the module-
    attribute form (``collectives.quantize_leaf`` under any alias),
    and aliased from-imports.
    """

    _VERBS = frozenset({"quantize_leaf", "dequantize_leaf"})

    def __init__(self, ctx, findings):
        self.ctx = ctx
        self.findings = findings
        self.mods: set[str] = set()
        self.funcs: dict[str, str] = {}

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.name == "ptype_tpu.parallel.collectives" and a.asname:
                self.mods.add(a.asname)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module in ("ptype_tpu.parallel", "ptype_tpu"):
            for a in node.names:
                if a.name == "collectives":
                    self.mods.add(a.asname or "collectives")
        elif node.module in ("ptype_tpu.parallel.collectives",
                             "ptype_tpu.serve_engine.migrate"):
            for a in node.names:
                if a.name in self._VERBS:
                    self.funcs[a.asname or a.name] = a.name
        self.generic_visit(node)

    def _flag(self, node: ast.Call, verb: str) -> None:
        self.findings.append(self.ctx.finding(
            node, "PT021",
            f"{verb} on the serving path outside serve_engine/"
            f"migrate.py — KV wire serialization has ONE home (the "
            f"migration module); a second codec call site forks the "
            f"wire format and breaks the per-block error-feedback "
            f"contract (residuals keyed by chain hash, one store)"))

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in self._VERBS:
            base = fn.value
            if isinstance(base, ast.Name) and base.id in (
                    self.mods or {"collectives"}):
                self._flag(node, fn.attr)  # collectives.quantize_leaf
            elif (isinstance(base, ast.Attribute)
                    and base.attr == "collectives"):
                self._flag(node, fn.attr)  # parallel.collectives.q...
        elif isinstance(fn, ast.Name) and fn.id in self.funcs:
            self._flag(node, self.funcs[fn.id])
        self.generic_visit(node)


@rule("PT021", "KV wire serialization outside the migration home",
      applies=lambda ctx: (ctx.in_pkg and ctx.in_dir("serve_engine")
                           and ctx.basename != "migrate.py"))
def check_pt021(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _KVWireCheck(ctx, findings).visit(ctx.tree)
    return findings


# ------------------------------------------------------------------ PT022


class _ParamGatherCheck(ast.NodeVisitor):
    """Flag full-tree param materialization inside ``train/``.

    The ZeRO-3 residency contract (ISSUE 17) keeps params resident as
    flat P(axis) shards; the ONLY place a full tree may be assembled
    is ``parallel/zero.py`` (``ZeroState.gather_params`` riding
    ``_bucket_gather_fn``).  Anything in ``train/`` that re-gathers —
    a raw ``all_gather``, an ad-hoc ``.gather()`` on a scattered
    handle, or ``pull(..., gather=True)`` against the store — forks
    that contract and silently reinflates per-replica memory back to
    the replicated footprint.  Delegating to the sanctioned API
    (``self._zero.gather_params()``) is fine and is not flagged.
    """

    def __init__(self, ctx, findings):
        self.ctx = ctx
        self.findings = findings

    def _flag(self, node: ast.Call, what: str) -> None:
        self.findings.append(self.ctx.finding(
            node, "PT022",
            f"{what} in train/ — full-tree param materialization has "
            f"ONE home (parallel/zero.py: ZeroState.gather_params / "
            f"_bucket_gather_fn); an ad-hoc gather here reinflates "
            f"per-replica memory to the replicated footprint and "
            f"dodges the zero3.param_gather progaudit pin"))

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        name = terminal_name(fn)
        if name == "all_gather":
            self._flag(node, "all_gather")
        elif isinstance(fn, ast.Attribute) and fn.attr == "gather":
            self._flag(node, ".gather()")
        elif name == "pull":
            for kw in node.keywords:
                if (kw.arg == "gather"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True):
                    self._flag(node, "pull(gather=True)")
                    break
        self.generic_visit(node)


@rule("PT022", "full-tree param allgather outside the ZeRO-3 home",
      applies=lambda ctx: ctx.in_pkg and ctx.in_dir("train"))
def check_pt022(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _ParamGatherCheck(ctx, findings).visit(ctx.tree)
    return findings


# ------------------------------------------------------------------ PT023

#: Callables whose positional axis-name argument makes a ``"data"``
#: literal a flat-axis collective construction.
_AXIS_CALLABLES = frozenset({
    "psum", "pmean", "psum_scatter", "all_gather", "all_to_all",
    "ppermute", "axis_index", "axis_size", "axis_n",
    "PartitionSpec", "P",
})

#: Keyword names that carry an axis name anywhere in the package.
_AXIS_KWARGS = frozenset({"axis", "mesh_axis", "axis_name"})

#: Callables whose dict-literal argument is mesh geometry.
_MESH_BUILDERS = frozenset({"build_mesh", "local_mesh"})


def _is_data(node) -> bool:
    return isinstance(node, ast.Constant) and node.value == "data"


class _FlatAxisLiteralCheck(ast.NodeVisitor):
    """Hard-coded ``"data"`` axis names outside ``parallel/``.

    The topology plane (ISSUE 18) made the data axis a VALUE, not a
    name: on a hierarchical mesh the flat ``"data"`` axis becomes the
    composite ``("inner", "outer")`` tuple, and every module that
    spells the literal instead of reading ``DATA_AXIS`` /
    ``topology.flat_axis`` / the store's ``.axis`` silently builds a
    1-D program that cannot ride the hierarchical decomposition —
    shardings stop matching, collectives launch over an axis the mesh
    no longer has. ``parallel/`` is the literal's one home
    (``topology.DATA_AXIS`` is defined there); everywhere else the
    axis name must flow from the topology descriptor or the object
    that owns the mesh. Catches the kwarg form (``axis="data"``),
    positional axis names handed to collective/sharding callables
    (``psum(x, "data")``, ``P("data")``), mesh-geometry dict keys
    (``build_mesh({"data": n})``), axis-name parameter defaults, and
    axis-keyed subscripts (``mesh.shape["data"]``,
    ``axis_sizes["data"]``).
    """

    def __init__(self, ctx, findings):
        self.ctx = ctx
        self.findings = findings

    def _flag(self, node, how: str) -> None:
        self.findings.append(self.ctx.finding(
            node, "PT023",
            f"hard-coded \"data\" axis name ({how}) outside "
            f"parallel/ — on a hierarchical mesh the flat axis is "
            f"the composite (\"inner\", \"outer\") tuple; spell it "
            f"as topology.DATA_AXIS / topology.flat_axis / the "
            f"owning object's .axis so the program rides the "
            f"topology plane instead of pinning a 1-D mesh"))

    def visit_Call(self, node: ast.Call) -> None:
        name = terminal_name(node.func)
        for kw in node.keywords:
            if kw.arg in _AXIS_KWARGS and _is_data(kw.value):
                self._flag(kw.value, f"{kw.arg}= keyword")
        if name in _AXIS_CALLABLES:
            for a in node.args:
                if _is_data(a):
                    self._flag(a, f"positional axis to {name}()")
        if name in _MESH_BUILDERS:
            for a in node.args:
                if isinstance(a, ast.Dict):
                    for k in a.keys:
                        if _is_data(k):
                            self._flag(k, f"mesh axis key in {name}()")
        self.generic_visit(node)

    def _defaults(self, node) -> None:
        args = node.args
        pos = args.posonlyargs + args.args
        for a, d in zip(pos[len(pos) - len(args.defaults):],
                        args.defaults):
            if a.arg in _AXIS_KWARGS and _is_data(d):
                self._flag(d, f"default for {a.arg}=")
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None and a.arg in _AXIS_KWARGS and _is_data(d):
                self._flag(d, f"default for {a.arg}=")
        self.generic_visit(node)

    visit_FunctionDef = visit_AsyncFunctionDef = _defaults

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if _is_data(node.slice):
            base = node.value
            attr = base.attr if isinstance(base, ast.Attribute) else (
                base.id if isinstance(base, ast.Name) else "")
            if attr == "shape" or "axis" in attr:
                self._flag(node, f"{attr}[\"data\"] subscript")
        self.generic_visit(node)


@rule("PT023", "hard-coded flat \"data\" axis name outside parallel/",
      applies=lambda ctx: ctx.in_pkg and not ctx.in_dir("parallel"))
def check_pt023(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _FlatAxisLiteralCheck(ctx, findings).visit(ctx.tree)
    return findings


# ------------------------------------------------------------------ PT024


class _RawTrafficRandomCheck(ast.NodeVisitor):
    """Raw ``random.*`` / ``np.random.*`` draws inside ``loadgen/``.

    A traffic trace is replay evidence — the capacity frontier, the
    spike drill, and any chaos-soak composition cite its seed — so
    determinism has ONE home: :mod:`ptype_tpu.loadgen.rng`
    (:class:`TraceRng`, forked streams, SHA-derived child seeds). A
    stray ``random.random()`` or ``np.random.poisson()`` anywhere
    else in the package silently breaks same-seed replay (module
    state shared across traces, process-salted hashing, draw-order
    coupling between schedule and population). Tracks plain imports,
    aliases (``import numpy.random as npr``), and ``from random
    import ...`` of draw functions.
    """

    #: from-imported stdlib draw verbs worth tracking by bare name.
    _VERBS = frozenset({
        "random", "randint", "randrange", "uniform", "choice",
        "choices", "shuffle", "sample", "expovariate", "gauss",
        "lognormvariate", "normalvariate", "paretovariate",
        "betavariate", "gammavariate", "triangular", "vonmisesvariate",
        "weibullvariate", "getrandbits",
    })

    def __init__(self, ctx, findings):
        self.ctx = ctx
        self.findings = findings
        #: names bound to the random / numpy.random modules
        self.rand_mods: set[str] = set()
        #: names bound to numpy itself (np.random.* chains)
        self.np_mods: set[str] = set()
        #: bare names from-imported from the random module
        self.funcs: set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            bound = a.asname or a.name.split(".")[0]
            if a.name == "random":
                self.rand_mods.add(bound)
            elif a.name in ("numpy", "numpy.random") and a.asname:
                (self.rand_mods if a.name == "numpy.random"
                 else self.np_mods).add(a.asname)
            elif a.name == "numpy":
                self.np_mods.add("numpy")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "random":
            for a in node.names:
                if a.name in self._VERBS or a.name == "Random":
                    self.funcs.add(a.asname or a.name)
        elif node.module == "numpy":
            for a in node.names:
                if a.name == "random":
                    self.rand_mods.add(a.asname or a.name)
        self.generic_visit(node)

    def _flag(self, node, what: str) -> None:
        self.findings.append(self.ctx.finding(
            node, "PT024",
            f"raw {what} inside loadgen/ — every traffic draw must "
            f"flow through the seeded RNG home "
            f"(loadgen/rng.py TraceRng) or same-seed replay breaks"))

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            base = f.value
            if (isinstance(base, ast.Name)
                    and base.id in self.rand_mods):
                self._flag(node, f"{base.id}.{f.attr}() draw")
            elif (isinstance(base, ast.Attribute)
                  and base.attr == "random"
                  and isinstance(base.value, ast.Name)
                  and base.value.id in self.np_mods):
                self._flag(
                    node, f"{base.value.id}.random.{f.attr}() draw")
        elif isinstance(f, ast.Name) and f.id in self.funcs:
            self._flag(node, f"{f.id}() draw (from random import)")
        self.generic_visit(node)


@rule("PT024", "raw random draw in loadgen/ outside the seeded RNG "
      "home",
      applies=lambda ctx: (ctx.in_pkg and ctx.in_dir("loadgen")
                           and ctx.basename != "rng.py"))
def check_pt024(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    _RawTrafficRandomCheck(ctx, findings).visit(ctx.tree)
    return findings


# ------------------------------------------------------------------ PT025


class _AdHocLatencyCheck(ast.NodeVisitor):
    """Flags every ``perf_counter`` call — the caller scopes WHERE."""

    def __init__(self, ctx, findings):
        self.ctx = ctx
        self.findings = findings
        self.mods: set[str] = set()
        self.funcs: set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.name == "time":
                self.mods.add(a.asname or "time")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "time":
            for a in node.names:
                if a.name == "perf_counter":
                    self.funcs.add(a.asname or a.name)
        self.generic_visit(node)

    def _flag(self, node: ast.Call) -> None:
        self.findings.append(self.ctx.finding(
            node, "PT025",
            "ad-hoc perf_counter latency measurement in request-path "
            "code — attribution has ONE home: gateway legs time "
            "through gateway/slo.py Stopwatch (which feeds the "
            "stage_ms histograms, exemplars, and the stage-breach "
            "page), engine legs through the serving ledger's seams. "
            "A private timer is a latency number no waterfall, "
            "exemplar, or budget will ever see"))

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (isinstance(fn, ast.Attribute)
                and fn.attr == "perf_counter"
                and isinstance(fn.value, ast.Name)
                and fn.value.id in (self.mods or {"time", "_time"})):
            self._flag(node)
        elif isinstance(fn, ast.Name) and fn.id in self.funcs:
            self._flag(node)
        self.generic_visit(node)


@rule("PT025", "ad-hoc perf_counter latency measurement outside the "
      "sanctioned timing seams",
      applies=lambda ctx: (ctx.in_pkg
                           and (ctx.in_dir("gateway")
                                or ctx.in_dir("serve_engine"))
                           and ctx.basename != "slo.py"))
def check_pt025(ctx: FileContext) -> list[Finding]:
    # gateway/slo.py is exempt by scope: it IS the sanctioned home
    # (Stopwatch + SLOTracker). serve_engine/ additionally carries
    # PT010 (any raw wall-clock read); PT025 adds the latency-specific
    # story so a gateway file moved there keeps the same verdict.
    findings: list[Finding] = []
    _AdHocLatencyCheck(ctx, findings).visit(ctx.tree)
    return findings
