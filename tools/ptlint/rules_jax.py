"""PT016/PT017 — the JAX-safety dataflow passes.

- **PT016 donation-safety**: an argument donated via
  ``donate_argnums`` is INVALID after the jitted call — its buffer was
  handed to XLA for reuse. Reading it afterwards either crashes
  ("buffer has been deleted") on hardware or, worse, silently reads
  whatever happened to still be resident under some backends. The pass
  maps every ``name = jax.jit(f, donate_argnums=...)`` binding (module,
  class or local scope), then at each call site of that binding checks
  whether a donated argument expression is loaded again later in the
  same function without an intervening rebind.

- **PT017 RNG-key-reuse**: the same ``jax.random`` key flowing into
  two draws without a ``split``/``fold_in`` between yields CORRELATED
  samples (identical, for the same draw shape) — the serving engine's
  exact-distribution contract dies silently. The pass tracks key
  names through a function in statement order: a second draw from an
  already-consumed key name with no rebinding between is a finding.
"""

from __future__ import annotations

import ast

from .core import FileContext, Finding, rule
from .scopes import ImportMap, index_loads_stores, terminal_name, unparse

# --------------------------------------------------------------- PT016


def _donated_indices(call: ast.Call) -> tuple | None:
    """The donate_argnums of a ``jax.jit``/``jit`` call, or None."""
    if terminal_name(call.func) != "jit":
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for elt in v.elts:
                if (isinstance(elt, ast.Constant)
                        and isinstance(elt.value, int)):
                    out.append(elt.value)
            return tuple(out)
        return None
    return None


def _collect_donating_bindings(tree: ast.AST) -> dict[str, tuple]:
    """binding expression text -> donated indices, for every
    ``<target> = jax.jit(..., donate_argnums=...)`` in the file."""
    out: dict[str, tuple] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        donated = _donated_indices(node.value)
        if not donated:
            continue
        for t in node.targets:
            out[unparse(t)] = donated
    return out


def _check_fn_pt016(ctx: FileContext, fn, bindings: dict,
                    findings: list[Finding]) -> None:
    loads, stores = index_loads_stores(fn)
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        donated = bindings.get(unparse(node.func))
        if not donated:
            continue
        call_end = getattr(node, "end_lineno", node.lineno)
        for idx in donated:
            if idx >= len(node.args):
                continue
            arg = node.args[idx]
            if not isinstance(arg, (ast.Name, ast.Attribute,
                                    ast.Subscript)):
                continue
            expr = unparse(arg)
            rebinds = [s for s in stores.get(expr, [])
                       if s >= node.lineno]
            for load_line in loads.get(expr, []):
                if load_line <= call_end:
                    continue
                if any(node.lineno <= s <= load_line
                       for s in rebinds):
                    break  # rebound (the donation idiom: x = f(x))
                findings.append(Finding(
                    ctx.path, load_line, "PT016",
                    f"'{expr}' was DONATED to {unparse(node.func)} "
                    f"(donate_argnums position {idx}, line "
                    f"{node.lineno}) and is read again here — the "
                    f"buffer now belongs to XLA (deleted-buffer "
                    f"crash on TPU, silent garbage elsewhere); "
                    f"rebind the result or drop the stale "
                    f"reference"))
                break  # one finding per donated arg per call


@rule("PT016", "donated argument read after the jitted call",
      applies=lambda ctx: ctx.in_pkg)
def check_pt016(ctx: FileContext) -> list[Finding]:
    bindings = _collect_donating_bindings(ctx.tree)
    if not bindings:
        return []
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _check_fn_pt016(ctx, node, bindings, findings)
    return findings


# --------------------------------------------------------------- PT017

#: jax.random callables that CONSUME a key but are key-plumbing, not
#: draws: a second use after them is still a bug, but they are how a
#: key is split into independent streams, so they never mark a key
#: "used" (the typical idiom rebinds: ``key, sub = split(key)`` —
#: the Store clears the name anyway).
_NON_DRAWS = frozenset({
    "split", "fold_in", "PRNGKey", "key", "key_data",
    "wrap_key_data", "clone", "key_impl",
})


class _Pt017Walker(ast.NodeVisitor):
    """Per-function linear scan: draw calls consume key names; a
    rebinding (Store) refreshes them."""

    def __init__(self, ctx, findings):
        self.ctx = ctx
        self.findings = findings
        self.imports = ImportMap(ctx.tree)
        self.rand_mods = self.imports.module_aliases("jax.random")
        self.from_draws = {
            local: orig
            for local, (mod, orig) in self.imports.from_names.items()
            if mod == "jax.random" and orig not in _NON_DRAWS}

    def _draw_verb(self, call: ast.Call) -> str | None:
        fn = call.func
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if fn.attr in _NON_DRAWS:
                return None
            if (isinstance(base, ast.Attribute)
                    and base.attr == "random"
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "jax"):
                return fn.attr          # jax.random.uniform(...)
            if (isinstance(base, ast.Name)
                    and base.id in self.rand_mods):
                return fn.attr          # jr.uniform / random.uniform
        elif isinstance(fn, ast.Name) and fn.id in self.from_draws:
            return self.from_draws[fn.id]
        return None

    @staticmethod
    def _walk_shallow(root):
        """ast.walk, but stopping at nested function defs (they get
        their own linear scan — re-scanning their bodies as part of
        the parent would double-report every nested draw)."""
        todo = list(ast.iter_child_nodes(root))
        while todo:
            node = todo.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                todo.extend(ast.iter_child_nodes(node))

    def _fn(self, node) -> None:
        used: dict[str, int] = {}   # key expr -> draw line
        # Walk in source order; track rebinds as they appear.
        for sub in sorted(
                [n for n in self._walk_shallow(node)
                 if isinstance(n, (ast.Call, ast.Name, ast.Attribute,
                                   ast.Subscript))],
                key=lambda n: (n.lineno, n.col_offset)):
            if isinstance(sub, (ast.Name, ast.Attribute,
                                ast.Subscript)):
                if isinstance(getattr(sub, "ctx", None),
                              (ast.Store, ast.Del)):
                    used.pop(unparse(sub), None)
                continue
            verb = self._draw_verb(sub)
            if verb is None:
                continue
            if not sub.args:
                continue
            key = sub.args[0]
            if not isinstance(key, (ast.Name, ast.Attribute,
                                    ast.Subscript)):
                continue
            expr = unparse(key)
            prev = used.get(expr)
            if prev is not None:
                self.findings.append(self.ctx.finding(
                    sub, "PT017",
                    f"key '{expr}' already fed a jax.random draw at "
                    f"line {prev} and flows into jax.random.{verb} "
                    f"with no split/fold_in between — the two draws "
                    f"are correlated (identical for equal shapes); "
                    f"split the key or fold_in a step counter"))
            else:
                used[expr] = sub.lineno
        # No recursion into nested defs from here: each function is
        # visited on its own by generic dispatch below.

    def visit_FunctionDef(self, node) -> None:
        self._fn(node)
        for stmt in node.body:
            self.generic_visit_nested(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def generic_visit_nested(self, node) -> None:
        """Descend looking for NESTED function defs only (their bodies
        get their own linear scan; re-scanning them as part of the
        parent would double-report)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                self.visit_FunctionDef(child)
            else:
                self.generic_visit_nested(child)


@rule("PT017", "same RNG key feeding two draws without a split",
      applies=lambda ctx: ctx.in_pkg)
def check_pt017(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    w = _Pt017Walker(ctx, findings)
    for node in ctx.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            w.visit_FunctionDef(node)
        else:
            w.generic_visit_nested(node)
    return findings
