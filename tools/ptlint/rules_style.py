"""The pyflakes-grade base checks, migrated verbatim from tools/lint.py:

- E722 bare except, B006 mutable default, E711 ==None/True/False,
  F541 placeholder-less f-string (one combined AST walk);
- F401 unused module-scope imports (``__init__.py`` re-export surfaces
  and ``_``-prefixed names exempt);
- F821 undefined names via the symtable module's scope analysis.

Behavior is pinned by the golden-output migration test
(tests/test_ptlint.py) — these must keep firing exactly where the old
walker fired.
"""

from __future__ import annotations

import ast
import builtins
import symtable

from .core import FileContext, Finding, rule

_IMPLICIT = {"__file__", "__name__", "__doc__", "__package__",
             "__spec__", "__loader__", "__builtins__", "__debug__",
             "__path__", "__class__", "NotImplemented"}
_BUILTINS = set(dir(builtins)) | _IMPLICIT


class _AstChecks(ast.NodeVisitor):
    def __init__(self, ctx: FileContext, findings: list[Finding]):
        self.ctx = ctx
        self.findings = findings
        self.imported: dict[str, int] = {}  # name -> lineno
        self.used: set[str] = set()
        self.exported: set[str] = set()

    def _f(self, node, code, msg):
        self.findings.append(self.ctx.finding(node, code, msg))

    # -- imports / usage for the unused-import pass (module level only)
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            name = a.asname or a.name.split(".")[0]
            if not name.startswith("_"):
                self.imported.setdefault(name, node.lineno)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return  # compiler directives, not bindings to "use"
        for a in node.names:
            if a.name == "*":
                continue
            name = a.asname or a.name
            if not name.startswith("_"):
                self.imported.setdefault(name, node.lineno)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if (isinstance(t, ast.Name) and t.id == "__all__"
                    and isinstance(node.value, (ast.List, ast.Tuple))):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant):
                        self.exported.add(str(elt.value))
        self.generic_visit(node)

    # -- style/bug checks
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._f(node, "E722", "bare except")
        self.generic_visit(node)

    def _check_defaults(self, node) -> None:
        for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                self._f(d, "B006", "mutable default argument")

    def visit_FunctionDef(self, node) -> None:
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        for op, comp in zip(node.ops, node.comparators):
            if (isinstance(op, (ast.Eq, ast.NotEq))
                    and isinstance(comp, ast.Constant)
                    and (comp.value is None or comp.value is True
                         or comp.value is False)):
                # == True/False/None: identity is the correct test.
                self._f(node, "E711",
                        f"comparison to {comp.value} with ==/!= "
                        f"(use is / is not)")
        self.generic_visit(node)

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if not any(isinstance(v, ast.FormattedValue)
                   for v in node.values):
            self._f(node, "F541", "f-string without placeholders")
        # No generic_visit: recursing into FormattedValue format specs
        # re-reports the same literal.


@rule("E7XX", "base style/bug checks (E722/B006/E711/F541) + F401")
def check_base(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    v = _AstChecks(ctx, findings)
    v.visit(ctx.tree)
    if not ctx.is_init:  # __init__ imports ARE the re-export surface
        for name, lineno in sorted(v.imported.items(),
                                   key=lambda kv: kv[1]):
            if name not in v.used and name not in v.exported:
                findings.append(Finding(
                    ctx.path, lineno, "F401",
                    f"{name!r} imported but unused"))
    return findings


def _scope_bound_names(table: symtable.SymbolTable) -> set[str]:
    bound = set()
    for sym in table.get_symbols():
        if sym.is_assigned() or sym.is_imported() or sym.is_parameter():
            bound.add(sym.get_name())
    for child in table.get_children():
        bound.add(child.get_name())  # nested def/class names
    return bound


@rule("F821", "undefined names via symtable scope analysis")
def check_undefined(ctx: FileContext) -> list[Finding]:
    findings: list[Finding] = []
    try:
        top = symtable.symtable(ctx.src, ctx.path, "exec")
    except SyntaxError:
        return findings  # already reported as E999

    module_bound = _scope_bound_names(top)

    def walk(table: symtable.SymbolTable, enclosing: set[str]) -> None:
        bound = enclosing | _scope_bound_names(table)
        for sym in table.get_symbols():
            name = sym.get_name()
            if not sym.is_referenced():
                continue
            if (sym.is_assigned() or sym.is_imported()
                    or sym.is_parameter() or sym.is_global()
                    or sym.is_declared_global() or sym.is_nonlocal()):
                continue
            if sym.is_free():  # bound in an enclosing function scope
                continue
            if name in bound or name in _BUILTINS:
                continue
            findings.append(Finding(
                ctx.path, table.get_lineno(), "F821",
                f"undefined name {name!r} "
                f"(scope {table.get_name()!r})"))
        for child in table.get_children():
            # Class scopes do not enclose their methods' name lookup.
            nxt = (enclosing | module_bound
                   if table.get_type() == "class" else bound)
            walk(child, nxt)

    walk(top, set())
    return findings
