#!/bin/bash
# TPU tunnel watcher. Probes backend init in a fresh process (a wedged
# tunnel HANGS init, so the probe runs under timeout); exits 0 the
# moment the chip answers so the caller can run `make tpu-validate`.
# Exits 1 when the watch window closes still-down (caller restarts).
# Budget: 2 probes x 60s + 2 x 180s sleep = 8 min < the 10-min cap the
# caller runs us under.
cd /root/repo || exit 2
for i in 1 2; do
  [ "$i" -gt 1 ] && sleep 180  # between probes only, not after the last
  if timeout 60 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" >/dev/null 2>&1; then
    echo "TPU up at $(date -u +%FT%TZ)" >> tpu_watch.log
    exit 0
  fi
done
exit 1
