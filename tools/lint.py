"""Static analysis for environments without ruff/flake8.

The reference gated CI on golangci-lint (/root/reference/.golangci.yml,
.travis.yml:1-11); this image bakes in no Python linter and installs
are barred, so `make lint` runs this stdlib-only checker instead (and
prefers `ruff check` when one is on PATH — see the Makefile).

Checks (pyflakes-grade, conservative to stay false-positive-free):

- syntax errors (ast.parse)
- unused imports (module scope; ``as _``-style and __init__ re-exports
  exempted — re-export surfaces exist to be imported FROM)
- undefined names, via the symtable module's scope analysis: a name
  loaded in a scope that neither that scope, an enclosing scope, the
  module, nor builtins binds
- mutable default arguments (list/dict/set displays)
- bare ``except:`` clauses
- ``== / !=`` comparisons against None / True / False
- f-strings with no placeholders
- PT001 (train/ only): an eager collective called inside a Python
  loop/comprehension — the per-leaf launch pattern the bucketed tree
  collectives exist to kill (parallel/collectives.tree_all_reduce)
- PT002 (ptype_tpu/ only): a bare ``time.sleep`` inside a loop — retry
  and poll loops must ride ptype_tpu.retry.Backoff (jittered
  exponential with a cap) so a fleet can't re-fire in lockstep into a
  dying node set; close-aware loops should use ``Event.wait``
- PT003 (ptype_tpu/ outside gateway/): ``new_client("llm")`` — a
  direct balanced client to the generation service bypasses the
  gateway's admission control, shedding, and load-aware routing
  (gateway.InferenceGateway / GatewayActor is the frontdoor)
- PT004 (ptype_tpu/ except __main__.py): a bare ``print(`` — framework
  diagnostics must ride the structured logs (trace-correlated via
  logs.KVLogger) or trace events, never stdout; __main__.py is the
  operator CLI whose stdout IS its contract
- PT005 (ptype_tpu/ except metrics.py): ``Counter(``/``Timing(``/
  ``Gauge(``/``Histogram(`` constructed directly — a family built
  outside a ``MetricsRegistry`` is invisible to the health plane's
  sampler (no series, no alerts); get it from a registry
  (``metrics.metrics.counter(...)``)
- PT006 (ptype_tpu/parallel/ only): a raw ``.astype(jnp.int8)`` /
  ``.astype("int8")`` narrowing outside the quantize helpers — an
  unscaled int8 cast silently destroys gradients (values outside
  ±127 saturate, sub-1 magnitudes round to zero); int8 wires must go
  through the block-scaled quantizers (``_q_int8_blockwise`` /
  ``quantize_leaf``), which pair every payload with its absmax scales
- PT011 (ptype_tpu/serve_engine/ only): a direct
  ``jax.random.categorical`` / ``jax.random.gumbel`` sampling call
  (bare, module-aliased, or from-imported) — acceptance sampling has
  ONE RNG home, models/generate.py's sampling helpers
  (``sample_token_rows`` / ``draft_propose_paged`` /
  ``spec_accept_rows``); an ad-hoc draw beside them silently rots the
  exactness contract (greedy bit-parity, residual-acceptance
  distribution) those helpers are contract-tested for
- PT010 (ptype_tpu/serve_engine/ only): a raw ``time.perf_counter()``
  / ``time.time()`` call (bare, module-aliased, or from-imported) —
  the engine's latency math lives in exactly one place, the serving
  ledger's seams (health/serving.py: enqueued / head_refused /
  admitted / chunk / first_token / tokens_emitted / iteration /
  retired); an ad-hoc stamp next to them drifts from the histograms
  and spans the ledger derives, and escapes the seam-cost probe that
  backs the <1%-overhead bar (``serving_ledger_overhead_pct``)
- PT007 (train/ only): ``optimizer.init(...)`` (full-tree optimizer
  state construction) outside the init/constructor helpers
  (``__init__`` / ``init_*`` / ``_init*``) — replicated whole-tree
  moments are exactly what the ZeRO-1 sharded update
  (parallel/zero.ZeroState — 1/N resident per replica) exists to
  eliminate; step/hot paths must consume the sharded or per-bucket
  state those helpers set up, never rebuild the full tree
- PT008 (ptype_tpu/ except metrics.py and health/profiling.py): a raw
  ``jax.profiler.start_trace`` / ``stop_trace`` call — the profiler is
  process-global and un-nestable, so an ad-hoc capture silently
  collides with the managed plane (the ptype.Profile endpoint,
  alert-triggered capture, cluster_profile); every capture must ride
  the rate-limited, artifact-managed seam in health/profiling.py (or
  the metrics.trace context manager, which profiling exempts as the
  one legacy local wrapper)
- PT009 (ptype_tpu/ outside serve_engine/ and models/): a raw
  ``init_cache`` call — a serving actor that allocates a contiguous
  full-reach KV bank pins ``n_slots × reach`` device memory whether
  or not any token exists, exactly the footprint the paged block pool
  (serve_engine.BlockPool: ref-counted blocks, prefix reuse, LRU
  eviction) replaces; serving code gets its KV storage from the pool
  (models/generate.py keeps init_cache for the solo compiled path)
- PT012 (ptype_tpu/ outside reconciler/ and serve.py): a direct
  ``ActorServer(...)`` construction — replica lifecycle has ONE home
  (reconciler/replica.py: spawn → warm → active → draining → exit,
  with registration, drain ordering, and the scale.* chaos seams);
  a server built beside it is a replica the reconciler can neither
  drain nor replace, invisible to the elastic control loop. Build
  through ``reconciler.replica.serve_actor`` / ``ReplicaHost`` (the
  operator CLI's ``serve`` command already does)

Exit 0 when clean; 1 with one ``path:line: code message`` per finding.
"""

from __future__ import annotations

import ast
import builtins
import os
import sys
import symtable

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Names importable from typing/__future__ semantics or runtime magic
#: that symtable reports oddly.
_IMPLICIT = {"__file__", "__name__", "__doc__", "__package__",
             "__spec__", "__loader__", "__builtins__", "__debug__",
             "__path__", "__class__", "NotImplemented"}
_BUILTINS = set(dir(builtins)) | _IMPLICIT


def _iter_py(paths: list[str]):
    for root in paths:
        if os.path.isfile(root):
            yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for f in filenames:
                if f.endswith(".py"):
                    yield os.path.join(dirpath, f)


def _scope_bound_names(table: symtable.SymbolTable) -> set[str]:
    bound = set()
    for sym in table.get_symbols():
        if sym.is_assigned() or sym.is_imported() or sym.is_parameter():
            bound.add(sym.get_name())
    for child in table.get_children():
        bound.add(child.get_name())  # nested def/class names
    return bound


def _check_undefined(path: str, src: str, findings: list[str]) -> None:
    try:
        top = symtable.symtable(src, path, "exec")
    except SyntaxError:
        return  # already reported by the ast pass

    module_bound = _scope_bound_names(top)

    def walk(table: symtable.SymbolTable, enclosing: set[str]) -> None:
        bound = enclosing | _scope_bound_names(table)
        for sym in table.get_symbols():
            name = sym.get_name()
            if not sym.is_referenced():
                continue
            if (sym.is_assigned() or sym.is_imported()
                    or sym.is_parameter() or sym.is_global()
                    or sym.is_declared_global() or sym.is_nonlocal()):
                continue
            if sym.is_free():  # bound in an enclosing function scope
                continue
            if name in bound or name in _BUILTINS:
                continue
            findings.append(
                f"{path}:{table.get_lineno()}: F821 undefined name "
                f"{name!r} (scope {table.get_name()!r})")
        for child in table.get_children():
            # Class scopes do not enclose their methods' name lookup.
            nxt = (enclosing | module_bound
                   if table.get_type() == "class" else bound)
            walk(child, nxt)

    walk(top, set())


class _AstChecks(ast.NodeVisitor):
    def __init__(self, path: str, is_init: bool, findings: list[str]):
        self.path = path
        self.is_init = is_init
        self.findings = findings
        self.imported: dict[str, int] = {}  # name -> lineno
        self.used: set[str] = set()
        self.exported: set[str] = set()

    def _f(self, node, code, msg):
        self.findings.append(f"{self.path}:{node.lineno}: {code} {msg}")

    # -- imports / usage for the unused-import pass (module level only)
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            name = a.asname or a.name.split(".")[0]
            if not name.startswith("_"):
                self.imported.setdefault(name, node.lineno)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return  # compiler directives, not bindings to "use"
        for a in node.names:
            if a.name == "*":
                continue
            name = a.asname or a.name
            if not name.startswith("_"):
                self.imported.setdefault(name, node.lineno)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if (isinstance(t, ast.Name) and t.id == "__all__"
                    and isinstance(node.value, (ast.List, ast.Tuple))):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant):
                        self.exported.add(str(elt.value))
        self.generic_visit(node)

    # -- style/bug checks
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._f(node, "E722", "bare except")
        self.generic_visit(node)

    def _check_defaults(self, node) -> None:
        for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                self._f(d, "B006", "mutable default argument")

    def visit_FunctionDef(self, node) -> None:
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        for op, comp in zip(node.ops, node.comparators):
            if (isinstance(op, (ast.Eq, ast.NotEq))
                    and isinstance(comp, ast.Constant)
                    and (comp.value is None or comp.value is True
                         or comp.value is False)):
                # == True/False/None: identity is the correct test.
                self._f(node, "E711",
                        f"comparison to {comp.value} with ==/!= "
                        f"(use is / is not)")
        self.generic_visit(node)

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self._f(node, "F541", "f-string without placeholders")
        # No generic_visit: recursing into FormattedValue format specs
        # re-reports the same literal.


#: Method/function names that dispatch one eager collective per call.
#: Calling any of these per pytree leaf inside a Python loop issues one
#: XLA launch per leaf — the anti-pattern the bucketed tree collectives
#: replace (one fused launch per dtype bucket).
_EAGER_COLLECTIVES = frozenset({
    "push", "push_scatter", "all_reduce", "all_gather",
    "reduce_scatter", "quantized_all_reduce",
    "quantized_reduce_scatter", "all_to_all", "ring_shift",
})


class _PerLeafCollectiveCheck(ast.NodeVisitor):
    """PT001: eager collective in a loop body (train/ files only —
    hot-path trainers must ride TensorStore.push_tree /
    collectives.tree_all_reduce, which bucket leaves into fused
    launches)."""

    def __init__(self, path: str, findings: list[str]):
        self.path = path
        self.findings = findings
        self.loop_depth = 0

    def _loop(self, node) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_AsyncFor = visit_While = _loop
    visit_ListComp = visit_SetComp = _loop
    visit_DictComp = visit_GeneratorExp = _loop

    def visit_Call(self, node: ast.Call) -> None:
        name = None
        if isinstance(node.func, ast.Attribute):
            name = node.func.attr
        elif isinstance(node.func, ast.Name):
            name = node.func.id
        if self.loop_depth and name in _EAGER_COLLECTIVES:
            self.findings.append(
                f"{self.path}:{node.lineno}: PT001 eager collective "
                f"{name!r} called in a per-leaf loop; bucket it "
                f"(TensorStore.push_tree / collectives.tree_all_reduce)")
        self.generic_visit(node)


#: Service names whose balanced-client path must go through the
#: gateway: raw ``new_client`` calls to them skip admission control
#: and least-loaded routing, so one slow replica re-serializes callers.
_GATED_SERVICES = frozenset({"llm"})


class _GatewayBypassCheck(ast.NodeVisitor):
    """PT003: a direct ``new_client("llm")`` inside ptype_tpu/ (the
    gateway package itself excepted). Framework code must front the
    generation fleet with gateway.InferenceGateway — the raw balancer
    is round-robin with no admission queue, exactly the path the
    gateway subsystem replaces."""

    def __init__(self, path: str, findings: list[str]):
        self.path = path
        self.findings = findings

    def visit_Call(self, node: ast.Call) -> None:
        name = None
        if isinstance(node.func, ast.Attribute):
            name = node.func.attr
        elif isinstance(node.func, ast.Name):
            name = node.func.id
        if (name == "new_client" and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value in _GATED_SERVICES):
            self.findings.append(
                f"{self.path}:{node.lineno}: PT003 direct "
                f"new_client({node.args[0].value!r}) bypasses the "
                f"inference gateway (admission control, shedding, "
                f"load-aware routing); use gateway.InferenceGateway "
                f"or a GatewayActor service")
        self.generic_visit(node)


class _BarePrintCheck(ast.NodeVisitor):
    """PT004: ``print(`` anywhere in ptype_tpu/ except __main__.py.

    A print is invisible to every observability tier this repo has —
    no level, no kv fields, no trace_id correlation, no capture in the
    KV formatter — so framework diagnostics must go through
    ``logs.get_logger`` (which auto-attaches the active span's
    trace_id/span_id) or trace span events. The operator CLI
    (__main__.py) is exempt: its stdout is machine-read output, not
    diagnostics."""

    def __init__(self, path: str, findings: list[str]):
        self.path = path
        self.findings = findings

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Name) and node.func.id == "print":
            self.findings.append(
                f"{self.path}:{node.lineno}: PT004 bare print() in "
                f"framework code; use logs.get_logger (trace-correlated "
                f"kv logging) or a trace span event")
        self.generic_visit(node)


#: Metric family classes that must come from a MetricsRegistry inside
#: the package: a directly-constructed family is invisible to the
#: health sampler's registry walk, so it produces no series and no
#: alert can see it.
_METRIC_FAMILIES = frozenset({"Counter", "Timing", "Gauge", "Histogram"})
#: Module aliases under which the repo imports ptype_tpu.metrics —
#: attribute calls through these are the direct-construction idiom;
#: other attribute bases (collections.Counter) are NOT flagged.
_METRICS_ALIASES = frozenset({"metrics", "metrics_mod"})


class _DirectMetricCheck(ast.NodeVisitor):
    """PT005: a metric family instantiated directly in ptype_tpu/
    (metrics.py itself excepted — it IS the factory). Both the bare
    name (``Counter("x")``) and the module-attribute form
    (``metrics.Counter("x")``) are flagged."""

    def __init__(self, path: str, findings: list[str]):
        self.path = path
        self.findings = findings

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        name = None
        if isinstance(fn, ast.Name) and fn.id in _METRIC_FAMILIES:
            name = fn.id
        elif (isinstance(fn, ast.Attribute)
              and fn.attr in _METRIC_FAMILIES
              and isinstance(fn.value, ast.Name)
              and fn.value.id in _METRICS_ALIASES):
            name = fn.attr
        if name is not None:
            self.findings.append(
                f"{self.path}:{node.lineno}: PT005 direct {name}() "
                f"construction bypasses the MetricsRegistry — the "
                f"health sampler can't see it (no series, no alerts); "
                f"use registry.{name.lower()}(name)")
        self.generic_visit(node)


#: Function-name prefixes sanctioned to narrow to int8 in
#: ptype_tpu/parallel/: the quantize helpers, which always pair the
#: cast with per-block absmax scales.
_QUANT_HELPER_PREFIXES = ("_q_", "quantize", "dequantize")


def _is_int8_arg(node: ast.expr) -> bool:
    """True for jnp.int8 / np.int8 / "int8" / dtype("int8")-shaped
    astype arguments."""
    if isinstance(node, ast.Constant):
        return node.value == "int8"
    if isinstance(node, ast.Attribute) and node.attr == "int8":
        return True
    if (isinstance(node, ast.Call) and node.args
            and isinstance(node.args[0], ast.Constant)):
        return node.args[0].value == "int8"
    return False


class _RawInt8CastCheck(ast.NodeVisitor):
    """PT006: ``.astype(int8)`` in ptype_tpu/parallel/ outside the
    quantize helpers. A bare int8 cast has no scale: gradient values
    saturate at ±127 and magnitudes below 1 round to zero — exactly
    the silent corruption the block-scaled quantizers
    (collectives._q_int8_blockwise / quantize_leaf) exist to prevent.
    """

    def __init__(self, path: str, findings: list[str]):
        self.path = path
        self.findings = findings
        self.fn_stack: list[str] = []

    def _fn(self, node) -> None:
        self.fn_stack.append(node.name)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = _fn

    def _sanctioned(self) -> bool:
        return any(name.startswith(_QUANT_HELPER_PREFIXES)
                   for name in self.fn_stack)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        dtype_args = list(node.args[:1]) + [
            kw.value for kw in node.keywords if kw.arg == "dtype"]
        if (isinstance(fn, ast.Attribute) and fn.attr == "astype"
                and any(_is_int8_arg(a) for a in dtype_args)
                and not self._sanctioned()):
            self.findings.append(
                f"{self.path}:{node.lineno}: PT006 raw .astype(int8) "
                f"narrowing outside the quantize helpers — an unscaled "
                f"int8 cast destroys gradients (saturation + underflow); "
                f"use collectives._q_int8_blockwise / quantize_leaf, "
                f"which carry per-block absmax scales")
        self.generic_visit(node)


#: Enclosing-function prefixes where constructing full-tree optimizer
#: state is sanctioned: constructors and the dedicated init helpers —
#: the one place a sharding-aware path (zero=True, overlap=True) can
#: intercept and replace the replicated state.
_OPT_INIT_SANCTIONED = ("__init__", "init_", "_init")


def _terminal_name(node: ast.expr) -> str | None:
    """The last identifier of a receiver expression: ``optimizer`` for
    ``self.optimizer``, ``default_optimizer`` for
    ``default_optimizer()``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Call):
        return _terminal_name(node.func)
    return None


class _FullTreeOptStateCheck(ast.NodeVisitor):
    """PT007: ``<...optimizer>.init(...)`` in train/ outside the
    init/constructor helpers. A full optimizer-state tree replicated
    per replica is the memory ceiling the sharded weight update
    removes; building one in a step/hot path silently reintroduces it
    (and reads as 'works' until the model grows)."""

    def __init__(self, path: str, findings: list[str]):
        self.path = path
        self.findings = findings
        self.fn_stack: list[str] = []

    def _fn(self, node) -> None:
        self.fn_stack.append(node.name)
        self.generic_visit(node)
        self.fn_stack.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = _fn

    def _sanctioned(self) -> bool:
        return any(name.startswith(_OPT_INIT_SANCTIONED)
                   for name in self.fn_stack)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr == "init"
                and not self._sanctioned()):
            recv = _terminal_name(fn.value)
            if recv is not None and (
                    "optimizer" in recv.lower() or recv in ("opt",
                                                            "_opt")):
                self.findings.append(
                    f"{self.path}:{node.lineno}: PT007 full-tree "
                    f"optimizer state constructed outside the init "
                    f"helpers ({recv}.init) — replicated moments cap "
                    f"trainable model size; hot paths must use the "
                    f"sharded state (parallel/zero.ZeroState, 1/N per "
                    f"replica) or the per-bucket states the init "
                    f"helpers set up")
        self.generic_visit(node)


class _RawProfilerTraceCheck(ast.NodeVisitor):
    """PT008: ``jax.profiler.start_trace`` / ``stop_trace`` (any
    ``*.profiler.start_trace`` attribute chain, or a bare
    ``start_trace``/``stop_trace`` imported from jax.profiler) in
    ptype_tpu/ outside metrics.py and health/profiling.py. The jax
    profiler is process-global: a raw call races the managed capture
    plane (ptype.Profile endpoint, alert-triggered capture,
    telemetry.cluster_profile) and leaves artifacts nothing tracks."""

    _VERBS = frozenset({"start_trace", "stop_trace"})

    def __init__(self, path: str, findings: list[str]):
        self.path = path
        self.findings = findings
        self.from_profiler: set[str] = set()

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module and node.module.endswith("profiler"):
            for a in node.names:
                if a.name in self._VERBS:
                    self.from_profiler.add(a.asname or a.name)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        hit = None
        if (isinstance(fn, ast.Attribute) and fn.attr in self._VERBS
                and isinstance(fn.value, ast.Attribute)
                and fn.value.attr == "profiler"):
            hit = fn.attr            # jax.profiler.start_trace(...)
        elif (isinstance(fn, ast.Attribute) and fn.attr in self._VERBS
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "profiler"):
            hit = fn.attr            # from jax import profiler
        elif (isinstance(fn, ast.Name)
                and fn.id in self.from_profiler):
            hit = fn.id              # from jax.profiler import start_trace
        if hit is not None:
            self.findings.append(
                f"{self.path}:{node.lineno}: PT008 raw jax.profiler."
                f"{hit} — the profiler is process-global and this "
                f"call races the managed capture plane; go through "
                f"health/profiling.py (start/stop/capture or the "
                f"ptype.Profile endpoint)")
        self.generic_visit(node)


class _RawCacheBankCheck(ast.NodeVisitor):
    """PT009: ``init_cache(...)`` (bare or attribute form — ``g.
    init_cache`` / ``gen.init_cache``) in ptype_tpu/ outside
    serve_engine/ and models/. A contiguous full-reach bank resident
    per slot is the memory ceiling the paged KV pool removes; serving
    code must allocate through serve_engine.BlockPool so resident
    memory tracks actual token counts (and prefix blocks are shared
    and evictable)."""

    def __init__(self, path: str, findings: list[str]):
        self.path = path
        self.findings = findings

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        name = None
        if isinstance(fn, ast.Name):
            name = fn.id
        elif isinstance(fn, ast.Attribute):
            name = fn.attr
        if name == "init_cache":
            self.findings.append(
                f"{self.path}:{node.lineno}: PT009 raw init_cache "
                f"full-reach bank allocation in serving code — "
                f"resident KV must come from the paged block pool "
                f"(serve_engine.BlockPool: ref-counted blocks, prefix "
                f"reuse, LRU eviction), not a contiguous "
                f"n_slots×reach bank")
        self.generic_visit(node)


class _RawReplicaServerCheck(ast.NodeVisitor):
    """PT012: ``ActorServer(...)`` constructed in ptype_tpu/ outside
    reconciler/ and serve.py — bare name or any ``*.ActorServer``
    attribute form. Serving-replica lifecycle (spawn/warm/activate/
    drain/replace, the registration that makes the gateway route to
    it, and the ``scale.spawn``/``scale.drain`` chaos seams) lives in
    exactly one place, reconciler/replica.py; a server constructed
    beside it serves traffic the elastic reconciler can neither drain
    gracefully nor replace on death."""

    def __init__(self, path: str, findings: list[str]):
        self.path = path
        self.findings = findings

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        name = None
        if isinstance(fn, ast.Name):
            name = fn.id
        elif isinstance(fn, ast.Attribute):
            name = fn.attr
        if name == "ActorServer":
            self.findings.append(
                f"{self.path}:{node.lineno}: PT012 direct ActorServer "
                f"construction outside the replica-lifecycle home — "
                f"the elastic reconciler can neither drain nor "
                f"replace a replica it didn't build; construct "
                f"through reconciler.replica.serve_actor / "
                f"ReplicaHost")
        self.generic_visit(node)


class _RawTimerCheck(ast.NodeVisitor):
    """PT010: ``time.perf_counter()`` / ``time.time()`` anywhere in
    ptype_tpu/serve_engine/ — bare attribute form, any module alias
    (``import time as _t``), or from-imports (``from time import
    perf_counter [as pc]``). The serving ledger (health/serving.py)
    is the engine's one timing home: its seams produce the stamps the
    TTFT/TPOT histograms AND the synthesized span tree derive from,
    and the seam-cost probe prices exactly those calls for the bench's
    overhead bar — a raw timer beside them is unpriced drift."""

    _VERBS = frozenset({"perf_counter", "time"})

    def __init__(self, path: str, findings: list[str]):
        self.path = path
        self.findings = findings
        #: Local names bound to the ``time`` module.
        self.mods: set[str] = set()
        #: Local name → original verb for from-imports of
        #: time.perf_counter / time.time (aliases included).
        self.funcs: dict[str, str] = {}

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.name == "time":
                self.mods.add(a.asname or "time")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "time":
            for a in node.names:
                if a.name in self._VERBS:
                    self.funcs[a.asname or a.name] = a.name
        self.generic_visit(node)

    def _flag(self, node: ast.Call, verb: str) -> None:
        self.findings.append(
            f"{self.path}:{node.lineno}: PT010 raw time.{verb} in "
            f"serve_engine/ — engine latency stamps must ride the "
            f"serving ledger's seams (health/serving.py: enqueued/"
            f"head_refused/admitted/chunk/first_token/tokens_emitted/"
            f"iteration/retired), the one timing home the histograms, "
            f"span tree, and seam-cost probe all derive from")

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr in self._VERBS
                and isinstance(fn.value, ast.Name)
                and fn.value.id in (self.mods or {"time", "_time"})):
            self._flag(node, fn.attr)
        elif isinstance(fn, ast.Name) and fn.id in self.funcs:
            self._flag(node, self.funcs[fn.id])
        self.generic_visit(node)


class _RawSamplingCheck(ast.NodeVisitor):
    """PT011: ``jax.random.categorical`` / ``jax.random.gumbel``
    anywhere in ptype_tpu/serve_engine/ — the ``*.random.<verb>``
    attribute chain (``jax.random.categorical(...)``), a module alias
    (``from jax import random``, ``import jax.random as jr``), or a
    from-import (``from jax.random import categorical [as c]``).
    Acceptance sampling must have exactly one RNG home —
    models/generate.py's sampling helpers (``sample_token_rows``,
    ``draft_propose_paged``, ``spec_accept_rows``), whose draw-for-draw
    and residual-acceptance contracts are what the spec-decoding
    exactness tests pin; a raw draw in the engine beside them is
    unpriced drift the contract tests can't see."""

    _VERBS = frozenset({"categorical", "gumbel"})

    def __init__(self, path: str, findings: list[str]):
        self.path = path
        self.findings = findings
        #: Local names bound to the jax.random module.
        self.rand_mods: set[str] = set()
        #: Local name → original verb for from-imports.
        self.funcs: dict[str, str] = {}

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.name == "jax.random" and a.asname:
                self.rand_mods.add(a.asname)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "jax":
            for a in node.names:
                if a.name == "random":
                    self.rand_mods.add(a.asname or "random")
        elif node.module == "jax.random":
            for a in node.names:
                if a.name in self._VERBS:
                    self.funcs[a.asname or a.name] = a.name
        self.generic_visit(node)

    def _flag(self, node: ast.Call, verb: str) -> None:
        self.findings.append(
            f"{self.path}:{node.lineno}: PT011 direct jax.random."
            f"{verb} sampling in serve_engine/ — acceptance sampling "
            f"has one RNG home (models/generate.py: sample_token_rows/"
            f"draft_propose_paged/spec_accept_rows, the contract-"
            f"tested helpers); a raw draw here silently rots the "
            f"exact-distribution contract")

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in self._VERBS:
            base = fn.value
            if (isinstance(base, ast.Attribute)
                    and base.attr == "random"
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "jax"):
                self._flag(node, fn.attr)   # jax.random.categorical
                # (rooted at `jax` only — np.random.gumbel and other
                # *.random receivers are not the guarded RNG)
            elif (isinstance(base, ast.Name)
                    and base.id in self.rand_mods):
                self._flag(node, fn.attr)   # random.categorical / jr.
        elif isinstance(fn, ast.Name) and fn.id in self.funcs:
            self._flag(node, self.funcs[fn.id])
        self.generic_visit(node)


class _SleepInLoopCheck(ast.NodeVisitor):
    """PT002: ``time.sleep`` (any ``time``/``_time`` alias) inside a
    loop body. Fixed-interval sleeps in retry/poll loops are the
    thundering-herd anti-pattern the shared ``ptype_tpu.retry.Backoff``
    exists to kill; ``Event.wait(timeout)`` is the close-aware
    alternative for monitor loops."""

    def __init__(self, path: str, findings: list[str]):
        self.path = path
        self.findings = findings
        self.loop_depth = 0

    def _loop(self, node) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_AsyncFor = visit_While = _loop

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (self.loop_depth
                and isinstance(fn, ast.Attribute) and fn.attr == "sleep"
                and isinstance(fn.value, ast.Name)
                and fn.value.id in ("time", "_time")):
            self.findings.append(
                f"{self.path}:{node.lineno}: PT002 bare time.sleep in a "
                f"loop; use ptype_tpu.retry.Backoff (jittered, capped) "
                f"or an Event.wait deadline")
        self.generic_visit(node)


def check_file(path: str, findings: list[str]) -> None:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        findings.append(f"{path}:{e.lineno}: E999 {e.msg}")
        return
    is_init = os.path.basename(path) == "__init__.py"
    raw: list[str] = []
    v = _AstChecks(path, is_init, raw)
    v.visit(tree)
    parts = os.path.normpath(path).split(os.sep)
    if "train" in parts:
        _PerLeafCollectiveCheck(path, raw).visit(tree)
        # Full-tree optimizer state belongs in init helpers only —
        # the seam the ZeRO-1 sharded update replaces.
        _FullTreeOptStateCheck(path, raw).visit(tree)
    if "ptype_tpu" in parts and os.path.basename(path) != "retry.py":
        # retry.py IS the sanctioned sleeper; everything else in the
        # package must go through it.
        _SleepInLoopCheck(path, raw).visit(tree)
    if "ptype_tpu" in parts and "gateway" not in parts:
        # The gateway package is the one sanctioned frontdoor.
        _GatewayBypassCheck(path, raw).visit(tree)
    if "ptype_tpu" in parts and os.path.basename(path) != "__main__.py":
        # __main__.py is the operator CLI: stdout IS its contract.
        _BarePrintCheck(path, raw).visit(tree)
    if "ptype_tpu" in parts and os.path.basename(path) != "metrics.py":
        # metrics.py IS the family factory; everything else must get
        # families from a MetricsRegistry so the sampler sees them.
        _DirectMetricCheck(path, raw).visit(tree)
    if "ptype_tpu" in parts and os.path.basename(path) not in (
            "metrics.py", "profiling.py"):
        # profiling.py IS the managed capture seam (and metrics.trace
        # the one legacy local wrapper); every other jax.profiler
        # start/stop races the process-global profiler.
        _RawProfilerTraceCheck(path, raw).visit(tree)
    if "ptype_tpu" in parts and "parallel" in parts:
        # The data plane's int8 narrowings must ride the scaled
        # quantize helpers — a bare cast is silent gradient loss.
        _RawInt8CastCheck(path, raw).visit(tree)
    if "ptype_tpu" in parts and "serve_engine" in parts:
        # The serving ledger (health/serving.py) is the engine's one
        # timing home: raw timers beside its seams drift from the
        # histograms/spans and escape the seam-cost overhead probe.
        _RawTimerCheck(path, raw).visit(tree)
        # models/generate.py's sampling helpers are the one RNG home:
        # an ad-hoc categorical/gumbel draw in the engine rots the
        # speculative-decoding exactness contract silently.
        _RawSamplingCheck(path, raw).visit(tree)
    if ("ptype_tpu" in parts and "serve_engine" not in parts
            and "models" not in parts):
        # serve_engine/ IS the paged pool; models/ holds init_cache
        # itself and the solo compiled path. Everywhere else (serve.py
        # and any future serving module), contiguous full-reach banks
        # are the footprint the pool replaces.
        _RawCacheBankCheck(path, raw).visit(tree)
    if ("ptype_tpu" in parts and "reconciler" not in parts
            and os.path.basename(path) != "serve.py"):
        # reconciler/replica.py IS the replica-lifecycle home (serve.py
        # is its actor library); a serving ActorServer built anywhere
        # else is invisible to the elastic control loop.
        _RawReplicaServerCheck(path, raw).visit(tree)
    if not is_init:  # __init__ imports ARE the re-export surface
        for name, lineno in sorted(v.imported.items(),
                                   key=lambda kv: kv[1]):
            if name not in v.used and name not in v.exported:
                raw.append(
                    f"{path}:{lineno}: F401 {name!r} imported but unused")
    _check_undefined(path, src, raw)
    # Honor `# noqa` suppressions and drop duplicates (order kept).
    lines = src.splitlines()
    seen = set()
    for finding in raw:
        try:
            lineno = int(finding.split(":", 2)[1])
        except (IndexError, ValueError):
            lineno = 0
        if 0 < lineno <= len(lines) and "noqa" in lines[lineno - 1]:
            continue
        if finding not in seen:
            seen.add(finding)
            findings.append(finding)


def main(argv: list[str]) -> int:
    paths = argv or [os.path.join(REPO, "ptype_tpu"),
                     os.path.join(REPO, "tests"),
                     os.path.join(REPO, "examples"),
                     os.path.join(REPO, "bench.py"),
                     os.path.join(REPO, "__graft_entry__.py"),
                     os.path.join(REPO, "tools")]
    findings: list[str] = []
    n = 0
    for path in _iter_py(paths):
        n += 1
        check_file(path, findings)
    for line in findings:
        print(line)
    print(f"lint: {n} files, {len(findings)} findings",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
