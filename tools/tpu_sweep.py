"""One-command TPU perf refresh — run when the tunnel is back.

Measures the rows docs/PERF.md needs re-validated after an outage and
prints them as a markdown table (plus one JSON line per row for
machine use). Each measurement is independently fault-isolated and
bounded, so a partial failure still yields the other rows.

Usage (from the repo root; PYTHONPATH must keep the TPU plugin path):
    PYTHONPATH=/root/repo:/root/.axon_site python tools/tpu_sweep.py

Measurement gotcha this script honors: ``jax.block_until_ready`` does
NOT drain the axon device tunnel — every timed section forces a scalar
readback (``float(...)``) before and after the clock.
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ROWS: list[dict] = []
#: --smoke: tiny shapes on whatever backend is present — validates the
#: script end to end without a TPU (rows are NOT perf numbers).
SMOKE = "--smoke" in sys.argv


def row(name: str, fn) -> None:
    t0 = time.time()
    try:
        rec = fn()
        rec["row"] = name
        rec["wall_s"] = round(time.time() - t0, 1)
        ROWS.append(rec)
        print(json.dumps(rec), flush=True)
    except Exception:  # noqa: BLE001 — isolate rows
        err = traceback.format_exc(limit=3).strip().splitlines()[-1]
        ROWS.append({"row": name, "error": err[-200:]})
        print(json.dumps(ROWS[-1]), flush=True)


def _train_tps(cfg, batch, seq, steps=30, warmup=3):
    import jax

    from ptype_tpu.parallel.mesh import build_mesh
    from ptype_tpu.train.data import synthetic_batches
    from ptype_tpu.train.trainer import Trainer

    devices = jax.devices()
    mesh = build_mesh({"data": len(devices)}, devices=devices)
    trainer = Trainer(cfg, mesh, sync_every=0)
    stream = synthetic_batches(cfg.vocab_size, batch, seq)
    for _ in range(warmup):
        out = trainer.step(next(stream))
    float(out["loss"])  # drain the tunnel, not just the dispatch queue
    t0 = time.perf_counter()
    for _ in range(steps):
        out = trainer.step(next(stream))
    final_loss = float(out["loss"])  # forces the full queue through
    dt = time.perf_counter() - t0
    return batch * seq * steps / dt, final_loss, len(devices)


def headline():
    from ptype_tpu.metrics import device_peak_tflops, mfu as mfu_of
    from ptype_tpu.models import transformer as tfm
    import jax

    if SMOKE:
        cfg = tfm.preset("tiny", attn_impl="xla")
        tps, loss, n = _train_tps(cfg, batch=2 * len(jax.devices()),
                                  seq=128, steps=3, warmup=1)
        seq = 128
    else:
        cfg = tfm.preset("optimus-125m", remat=True,
                         remat_policy="dots", attn_impl="flash")
        tps, loss, n = _train_tps(cfg, batch=16, seq=1024)
        seq = 1024
    m = mfu_of(tps, tfm.flops_per_token(cfg, seq), n,
               device_peak_tflops(jax.devices()[0]))
    return {"tok_s_chip": round(tps / n, 1), "mfu": round(m, 4),
            "loss": round(loss, 3)}


def long_context():
    from ptype_tpu.metrics import device_peak_tflops, mfu as mfu_of
    from ptype_tpu.models import transformer as tfm
    import jax

    if SMOKE:
        cfg = tfm.preset("tiny", attn_impl="xla", max_seq=512)
        tps, loss, n = _train_tps(cfg, batch=len(jax.devices()),
                                  seq=512, steps=2, warmup=1)
        seq = 512
    else:
        cfg = tfm.preset("optimus-125m", remat=True,
                         remat_policy="dots", attn_impl="flash",
                         max_seq=8192)
        tps, loss, n = _train_tps(cfg, batch=2, seq=8192, steps=10)
        seq = 8192
    m = mfu_of(tps, tfm.flops_per_token(cfg, seq), n,
               device_peak_tflops(jax.devices()[0]))
    return {"tok_s_chip": round(tps / n, 1), "mfu": round(m, 4),
            "loss": round(loss, 3)}


def decode():
    import jax
    import jax.numpy as jnp

    from ptype_tpu.models import generate as gen
    from ptype_tpu.models import transformer as tfm

    cfg = tfm.preset("tiny" if SMOKE else "optimus-125m",
                     attn_impl="xla")
    params = jax.jit(lambda r: tfm.init_params(r, cfg))(
        jax.random.PRNGKey(0))
    B, new = (2, 8) if SMOKE else (8, 64)
    prompts = jnp.zeros((B, 16), jnp.int32)
    toks = gen.generate(params, cfg, prompts, max_new_tokens=new)
    int(toks[0, -1])  # compile + drain
    t0 = time.perf_counter()
    toks = gen.generate(params, cfg, prompts, max_new_tokens=new)
    int(toks[0, -1])
    dt = time.perf_counter() - t0
    return {"decode_tok_s": round(B * new / dt, 1), "batch": B,
            "new_tokens": new}


def store_vs_gspmd():
    import jax

    from ptype_tpu.models import transformer as tfm
    from ptype_tpu.parallel.mesh import build_mesh
    from ptype_tpu.parallel.tensorstore import TensorStore
    from ptype_tpu.train.data import synthetic_batches
    from ptype_tpu.train.store_dp import StoreDPTrainer

    import jax as _jax

    B, S, steps = ((2 * len(_jax.devices()), 64, 2) if SMOKE
                   else (8, 512, 10))
    cfg = tfm.preset("tiny" if SMOKE else "optimus-125m",
                     attn_impl="xla")
    g_tps, _, n = _train_tps(cfg, batch=B, seq=S, steps=steps,
                             warmup=1 if SMOKE else 3)

    devices = jax.devices()
    mesh = build_mesh({"data": len(devices)}, devices=devices)
    st = StoreDPTrainer(cfg, TensorStore(mesh))
    stream = synthetic_batches(cfg.vocab_size, B, S)
    for _ in range(1 if SMOKE else 3):
        st.step(next(stream))  # store step blocks itself (loss float)
    t0 = time.perf_counter()
    for _ in range(steps):
        st.step(next(stream))
    dt = time.perf_counter() - t0
    s_tps = B * S * steps / dt
    return {"gspmd_tok_s": round(g_tps, 1),
            "store_tok_s": round(s_tps, 1),
            "ratio": round(s_tps / g_tps, 3), "n_chips": n}


def main() -> int:
    # A WEDGED tunnel hangs backend init (no exception — the bench.py
    # probe lesson): --smoke pins CPU before any backend initializes
    # so plumbing validation works under an outage, and the real sweep
    # probes in a bounded subprocess instead of hanging forever.
    if SMOKE:
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import subprocess

        try:
            p = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True, timeout=60, env=dict(os.environ))
            ok = p.returncode == 0
        except subprocess.TimeoutExpired:
            ok = False
        if not ok:
            print("backend probe hung/failed (wedged tunnel?); "
                  "re-run when hardware answers (use --smoke to "
                  "validate the plumbing off-TPU)", file=sys.stderr)
            return 42
    import jax

    if jax.devices()[0].platform != "tpu" and not SMOKE:
        print("no TPU attached; refusing to record CPU numbers as a "
              "TPU sweep (use --smoke to validate the plumbing)",
              file=sys.stderr)
        return 42
    kind = jax.devices()[0].device_kind
    row("headline b16 S1024 flash+dots", headline)
    row("long-context S8192", long_context)
    row("kv-cache decode 125m", decode)
    row("store vs gspmd (S512 b8)", store_vs_gspmd)

    print(f"\n## TPU sweep ({kind}, {time.strftime('%Y-%m-%d %H:%MZ', time.gmtime())})\n")
    print("| row | result |")
    print("|---|---|")
    for r in ROWS:
        body = {k: v for k, v in r.items() if k not in ("row",)}
        print(f"| {r['row']} | `{json.dumps(body)}` |")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
